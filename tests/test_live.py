"""Live monitoring layer (bcfl_tpu.telemetry.live, OBSERVABILITY.md §6) —
tier-1.

Three contracts, each load-bearing for the long-horizon soak
(scripts/dist_soak.py) that gates on the monitor live:

1. **Tailer parity** — an incremental tailer fed ANY chunking of a
   stream's bytes (one byte at a time, mid-line, mid-frame) yields the
   same events and the same finalize meta as the batch
   :func:`read_stream`, including the subtle torn-tail classifications.
2. **Streaming-vs-batch invariant parity** — on every seeded fixture from
   tests/test_telemetry.py (clean + each firing corruption), the
   streaming checkers' final verdicts equal
   ``run_invariants(causal_order(events))`` exactly, regardless of chunk
   boundaries or cross-stream interleave. A live monitor that disagrees
   with the post-hoc trace would make the soak's verdict meaningless.
3. **Health + alert lifecycle** — one health record per merge with the
   declared rollup fields; alerts fire once, heal once, and only
   violations / unhealed CRITICAL alerts gate the monitor's exit code
   (an expected byzantine trust collapse is a warn, not a failure).
"""

import json
import os

import pytest

import test_telemetry as tt
from bcfl_tpu import telemetry as T
from bcfl_tpu.telemetry.invariants import INVARIANTS, run_invariants
from bcfl_tpu.telemetry.live import (
    CRITICAL,
    STREAMING_CHECKS,
    WARN,
    AlertManager,
    AlertThresholds,
    HealthRollup,
    LiveCollator,
    StreamingInvariantSuite,
    StreamTailer,
    evaluate_health_alerts,
    monitor_main,
)

pytestmark = pytest.mark.telemetry

_ev, _send, _recv = tt._ev, tt._send, tt._recv
_merge, _end, _arrival = tt._merge, tt._end, tt._arrival


# ------------------------------------------------------------------ fixtures


def _quarantined_merge_run():
    """A leader that merges an arrival from a peer its own tracker holds
    QUARANTINED at merge time (scope='peer' — the dist lane)."""
    return tt._clean_run() + [
        # real dist streams carry the tracker's evidence row (every fault
        # path goes through _note) — required so slowness_is_not_malice
        # sees a non-slowness cause for the quarantine below
        _ev("rep.dist_evidence", "B", 5, 12.4, target="A",
            source="ledger_auth", fault=1.0),
        _ev("rep.evidence", "B", 6, 12.5, client="A", fault=1.0),
        _ev("rep.transition", "B", 7, 12.6, client="A", trust=0.05,
            scope="peer", **{"from": "suspect", "to": "quarantined"}),
        _merge("B", 8, 13.0, version=3, arrivals=[_arrival("A", 2)],
               component=["A", "B"], chain_len=6, head8="cc",
               rewrite=False),
        _send("A", 2, 12.8, to="B", msg_id=2),
        _recv("B", 9, 12.9, src="A", msg_id=2),
    ]


def _fixtures():
    """(name, events, firing_rules) — every seeded corruption from
    tests/test_telemetry.py plus the quarantined-merge lane, and the
    legal twins that must stay silent."""
    out = []
    out.append(("clean", tt._clean_run(), set()))

    ev = tt._clean_run()
    ev[3]["arrivals"] = [_arrival("A", 0)]
    out.append(("double_merge", ev, {"no_double_merge"}))

    ev = tt._clean_run()
    ev[3]["arrivals"] = [{"peer": "A", "staleness": 0}]
    out.append(("identityless_arrival", ev, {"no_double_merge"}))

    remerge = _merge("B", 0, 30.0, version=1, arrivals=[_arrival("A", 0)],
                     component=["A", "B"], chain_len=2, head8="aa",
                     rewrite=False)
    remerge["pid"] = 99999
    out.append(("fresh_incarnation_remerge", tt._clean_run() + [remerge],
                set()))

    ev = tt._clean_run()
    del ev[5]  # B never saw msg 1, yet A recorded it acked
    out.append(("lost_acked", ev, {"acked_not_lost"}))

    ev2 = [e for e in ev if not (e["ev"] == "run.end"
                                 and e["peer"] == "B")]
    out.append(("lost_acked_no_close", ev2, set()))

    ev3 = [dict(e) for e in ev]
    for e in ev3:
        if e["peer"] == "B" and e["seq"] >= 3:
            e["pid"] = 4242
    out.append(("lost_acked_two_pids", ev3, set()))

    ev4 = [dict(e) for e in ev]
    for e in ev4:
        if e["ev"] == "send" and e.get("msg_id") == 1:
            e["wall_s"] = 30.0
    out.append(("lost_acked_past_grace", ev4, set()))

    ev = tt._clean_run()
    ev[2]["component"] = ["B", "C"]
    out.append(("cross_partition", ev, {"no_cross_partition_merge"}))

    trans = _ev("rep.transition", "B", 5, 13.0, client=2, trust=0.1,
                **{"from": "suspect", "to": "quarantined"})
    out.append(("quarantine_no_evidence", tt._clean_run() + [trans],
                {"quarantine_evidence"}))
    evid = _ev("rep.evidence", "B", 4, 12.5, client=2, fault=1.0)
    out.append(("quarantine_with_evidence",
                tt._clean_run() + [evid, dict(trans, seq=6)], set()))
    # a resumed follower's from="restored" re-declaration carries no
    # local evidence by design (absorbed from the leader's chain rows)
    restored = _ev("rep.transition", "B", 5, 13.0, client=2, trust=0.3,
                   scope="peer",
                   **{"from": "restored", "to": "quarantined"})
    out.append(("quarantine_restored_exempt", tt._clean_run() + [restored],
                set()))

    shrink = _ev("ledger", "B", 5, 14.0, op="append", chain_len=1,
                 rewrite=False, head8="cc")
    out.append(("shrinking_chain", tt._clean_run() + [shrink],
                {"monotone_heads"}))
    out.append(("shrink_rewrite_exempt",
                tt._clean_run() + [dict(shrink, op="resync",
                                        rewrite=True)], set()))
    fresh = dict(_ev("ledger", "B", 0, 30.0, op="commit", chain_len=1,
                     rewrite=False, head8="dd"), pid=99999)
    out.append(("shrink_fresh_pid_exempt", tt._clean_run() + [fresh],
                set()))

    out.append(("quarantined_merge", _quarantined_merge_run(),
                {"no_quarantined_merge"}))

    # gray-failure lane (ROBUSTNESS.md §11): a peer-scoped quarantine whose
    # only dist evidence is the phi estimator's slowness feed is the exact
    # bug the lane forbids — slow must never be treated as malicious. The
    # rep.evidence row keeps quarantine_evidence silent so the new rule
    # fires alone; the legal twin adds one non-slowness evidence row.
    slow_ev = _ev("rep.dist_evidence", "B", 5, 12.4, target="A",
                  source="slowness", fault=0.4, slow=0.4)
    slow_rep = _ev("rep.evidence", "B", 6, 12.5, client="A", fault=1.0)
    slow_trans = _ev("rep.transition", "B", 7, 12.6, client="A",
                     trust=0.05, scope="peer",
                     **{"from": "suspect", "to": "quarantined"})
    out.append(("slowness_only_quarantine",
                tt._clean_run() + [slow_ev, slow_rep, slow_trans],
                {"slowness_is_not_malice"}))
    malice_ev = _ev("rep.dist_evidence", "B", 6, 12.45, target="A",
                    source="robust_outlier", fault=1.0)
    out.append(("slowness_plus_malice_quarantine",
                tt._clean_run() + [slow_ev, malice_ev,
                                   dict(slow_rep, seq=7),
                                   dict(slow_trans, seq=8)], set()))

    # storage-repair lanes (ROBUSTNESS.md §10): an adopt must consume a
    # verified-ok STATE_SYNC in its own incarnation...
    adopt = _ev("state.sync.adopt", "B", 5, 21.0, version=3, src=0)
    out.append(("unauthenticated_adopt", tt._clean_run() + [adopt],
                {"repair_authenticated"}))
    verify = _ev("state.sync.verify", "B", 5, 20.5, ok=True, src=0,
                 version=3)
    out.append(("authenticated_adopt",
                tt._clean_run() + [verify, dict(adopt, seq=6)], set()))
    # ...and a restarted peer may not persist a chain below an earlier
    # incarnation's committed high-water unless it repaired forward first
    save_hi = _ev("ckpt.save", "B", 5, 21.0, step=3, chain_len=6, gc=0)
    save_lo = _ev("ckpt.save", "B", 0, 30.0, pid=99999, step=1,
                  chain_len=2, gc=0)
    out.append(("rollback_readmission",
                tt._clean_run() + [save_hi, save_lo],
                {"no_rollback_readmission"}))
    out.append(("rollback_repaired_exempt",
                tt._clean_run() + [
                    save_hi,
                    _ev("state.sync.verify", "B", 0, 29.0, pid=99999,
                        ok=True, src=0, version=1),
                    _ev("state.sync.adopt", "B", 1, 29.5, pid=99999,
                        version=1, src=0),
                    dict(save_lo, seq=2)], set()))
    return out


def _streams_of(events):
    """Split a fixture into per-peer stream byte blobs, preserving the
    fixture's list order within each peer (= physical file order)."""
    by_peer = {}
    for e in events:
        by_peer.setdefault(str(e["peer"]), []).append(e)
    return {p: b"".join(json.dumps(e).encode() + b"\n" for e in evs)
            for p, evs in by_peer.items()}


def _stream_verdict(events, chunk):
    """Feed the fixture through tailers + the streaming suite with a
    round-robin cross-stream interleave at the given chunk size."""
    streams = _streams_of(events)
    tailers = {p: StreamTailer(p) for p in streams}
    suite = StreamingInvariantSuite()
    offs = dict.fromkeys(streams, 0)
    progressed = True
    while progressed:
        progressed = False
        for p, data in streams.items():
            o = offs[p]
            if o >= len(data):
                continue
            progressed = True
            piece = data[o:o + chunk]
            offs[p] = o + len(piece)
            for e in tailers[p].feed_bytes(piece):
                suite.feed(e)
    for p in streams:
        tail_e, _meta = tailers[p].finalize()
        if tail_e is not None:
            suite.feed(tail_e)
    return suite.finalize()


def _norm(verdict):
    return {k: sorted(json.dumps(v, sort_keys=True) for v in vs)
            for k, vs in verdict.items()}


# ------------------------------------------------------------ tailer parity


def _tailer_replay(data, chunk):
    t = StreamTailer("x")
    evs = []
    for i in range(0, len(data), chunk):
        evs.extend(t.feed_bytes(data[i:i + chunk]))
    tail_e, meta = t.finalize()
    if tail_e is not None:
        evs.append(tail_e)
    return evs, meta


@pytest.mark.parametrize("chunk", [1, 3, 17, 1 << 20])
def test_tailer_matches_read_stream_on_damaged_bytes(tmp_path, chunk):
    """Every torn/corrupt classification read_stream makes, the tailer
    must make identically — including newline-terminated garbage at EOF
    (torn, not corrupt) and a parseable unterminated final line (an
    event)."""
    cases = {
        # clean close
        "clean": b'{"ev":"round","peer":0,"seq":0,"round":0,"wall_s":1}\n',
        # torn final line (SIGKILL mid-write)
        "torn": b'{"ev":"round","peer":0,"seq":0,"round":0,"wall_s":1}\n'
                b'{"ev":"round","pee',
        # corrupt middle + clean end
        "corrupt_mid": b'{"ev":"round","peer":0,"seq":0}\nGARBAGE{{{\n'
                       b'{"ev":"round","peer":0,"seq":1}\n',
        # newline-terminated garbage at EOF: read_stream calls it TORN
        "torn_terminated": b'{"ev":"round","peer":0,"seq":0}\nGARB{{\n',
        # a final line with no newline that PARSES is a valid event
        "parseable_tail": b'{"ev":"round","peer":0,"seq":0}\n'
                          b'{"ev":"round","peer":0,"seq":1}',
        # whitespace-only tail is ignored, not torn
        "ws_tail": b'{"ev":"round","peer":0,"seq":0}\n   ',
        # empty stream
        "empty": b"",
    }
    for name, data in cases.items():
        path = str(tmp_path / f"events_{name}.jsonl")
        with open(path, "wb") as f:
            f.write(data)
        batch_events, batch_meta = T.read_stream(path)
        evs, meta = _tailer_replay(data, chunk)
        assert evs == batch_events, (name, chunk)
        assert meta["events"] == batch_meta["events"], (name, chunk)
        assert meta["torn_tail"] == batch_meta["torn_tail"], (name, chunk)
        assert meta["corrupt_lines"] == batch_meta["corrupt_lines"], \
            (name, chunk)


def test_tailer_torn_tail_completes_later(tmp_path):
    """A torn tail is PENDING, not corrupt: when the writer's next flush
    completes the line, the held prefix joins it into one event."""
    path = str(tmp_path / "events_peer0.jsonl")
    line = json.dumps({"ev": "round", "peer": 0, "seq": 0, "round": 0,
                       "wall_s": 0.1}).encode() + b"\n"
    with open(path, "wb") as f:
        f.write(line[:10])
    t = StreamTailer(path)
    assert t.poll() == []           # mid-write: nothing completed yet
    assert t.corrupt_so_far == 0    # and nothing counted corrupt
    with open(path, "ab") as f:
        f.write(line[10:])
    evs = t.poll()
    assert len(evs) == 1 and evs[0]["round"] == 0
    _tail, meta = t.finalize()
    assert meta == {"path": path, "events": 1, "torn_tail": False,
                    "corrupt_lines": 0}


def test_tailer_bounded_reads(tmp_path):
    """poll() with a tiny chunk budget still drains the whole backlog."""
    path = str(tmp_path / "events_peer0.jsonl")
    w = T.EventWriter(path, peer=0, flush_every=1)
    for r in range(50):
        w.emit("round", round=r, wall_s=0.1)
    w.close()
    t = StreamTailer(path)
    evs = t.poll(chunk_bytes=7)
    assert [e["round"] for e in evs] == list(range(50))


# ---------------------------------------------- streaming invariant parity


def test_streaming_registry_mirrors_batch():
    assert set(STREAMING_CHECKS) == set(INVARIANTS)


@pytest.mark.parametrize("chunk", [1, 3, 17, 1 << 20])
def test_streaming_batch_parity_all_fixtures(chunk):
    """THE parity contract: on every seeded fixture, streaming verdicts ==
    batch verdicts under adversarial chunk boundaries."""
    for name, events, firing in _fixtures():
        batch = run_invariants(T.causal_order(events))
        stream = _stream_verdict(events, chunk)
        assert _norm(stream) == _norm(batch), (name, chunk)
        fired = {k for k, v in stream.items() if v}
        assert fired == firing, (name, chunk, fired)


def test_streaming_violations_fire_before_finalize():
    """Liveness: the decidable violations surface during feed, not only
    at finalize — the soak's fail-fast gate depends on it."""
    for name, events, firing in _fixtures():
        if not firing:
            continue
        streams = _streams_of(events)
        suite = StreamingInvariantSuite()
        tailers = {p: StreamTailer(p) for p in streams}
        for p, data in streams.items():
            for e in tailers[p].feed_bytes(data):
                suite.feed(e)
        live = {k for k, c in suite.checks.items() if c.out}
        assert firing <= live, (name, live)


def test_streaming_acked_retracts_on_receiver_restart():
    """A verdict fired against a receiver whose stream later shows a
    second incarnation is retracted (the batch check never judges a
    restarted receiver)."""
    events = tt._clean_run()
    del events[5]                       # the lost-acked corruption...
    suite = StreamingInvariantSuite()
    for e in events:
        suite.feed(e)
    assert suite.checks["acked_not_lost"].out   # fired live
    # ...then a restarted incarnation of B appends to the same stream
    late = _ev("run.start", "B", 0, 30.0, role="peer")
    late["pid"] = 99999
    suite.feed(late)
    assert suite.checks["acked_not_lost"].out == []
    assert suite.finalize()["acked_not_lost"] == []
    batch = run_invariants(T.causal_order(events + [late]))
    assert batch["acked_not_lost"] == []


# ------------------------------------------------------------------- health


def _soak_like_events():
    return [
        _ev("run.start", "B", 0, 9.0, role="peer"),
        _send("A", 0, 10.0, to="B", msg_id=0, **{}),
        dict(_send("A", 1, 10.5, to="B", msg_id=1), bytes=1000),
        _recv("B", 1, 10.6, src="A", msg_id=0),
        _recv("B", 2, 10.7, src="A", msg_id=1),
        _ev("resource", "B", 3, 10.8, rss_gb=1.5, cpu_percent=42.0),
        dict(_merge("B", 4, 11.0, version=1,
                    arrivals=[_arrival("A", 0, staleness=1, weight=2.0),
                              _arrival("A", 1, staleness=3, weight=1.0)],
                    component=["A", "B"]),
             trust={"A": 0.9, "B": 1.0}, effective_rank=1.8),
        dict(_send("A", 2, 11.5, to="B", msg_id=2), bytes=500),
        _recv("B", 5, 11.6, src="A", msg_id=2),
        dict(_merge("B", 6, 14.0, version=2,
                    arrivals=[_arrival("A", 2, staleness=0, weight=1.0)],
                    component=["A", "B"]),
             trust={"A": 0.2, "B": 1.0}),
    ]


def test_health_rollup_per_merge_record():
    h = HealthRollup()
    recs = [r for r in map(h.feed, _soak_like_events()) if r is not None]
    assert len(recs) == 2
    r1, r2 = recs
    assert r1["round"] == 1 and r1["arrivals"] == 2
    assert r1["bytes_wire"] == 1000 and r1["sends_ok"] == 2
    assert r1["recv_accepted"] == 2
    assert r1["staleness_p50"] == 1 and r1["staleness_p95"] == 3
    assert (r1["weight_min"], r1["weight_max"]) == (1.0, 2.0)
    assert r1["trust"] == {"A": 0.9, "B": 1.0}
    assert r1["effective_rank"] == 1.8
    assert r1["resource"]["B"]["rss_gb"] == 1.5
    assert r1["round_gap_s"] is None
    # window counters reset per record; the gap spans merge-to-merge
    assert r2["bytes_wire"] == 500 and r2["sends_ok"] == 1
    assert abs(r2["round_gap_s"] - 3.0) < 1e-9
    assert r2["trust"]["A"] == 0.2


def test_alert_lifecycle_fire_heal_and_severity_gate():
    th = AlertThresholds(trust_warn=0.35, rss_critical_gb=2.0)
    alerts = AlertManager(th)
    h = HealthRollup()
    fired = []
    for e in _soak_like_events():
        rec = h.feed(e)
        if rec is not None:
            fired.extend(evaluate_health_alerts(alerts, rec))
    # round 2 dropped A's trust below the floor: exactly one warn fire
    trust_alerts = [a for a in fired if a["what"] == "trust_low"]
    assert len(trust_alerts) == 1
    assert trust_alerts[0]["severity"] == WARN
    assert trust_alerts[0]["key"] == "A"
    # a warn never gates: no unhealed criticals
    assert alerts.unhealed(CRITICAL) == []
    # recovery heals exactly once
    rec = h.feed(dict(_merge("B", 7, 15.0, version=3,
                             arrivals=[_arrival("A", 3)],
                             component=["A", "B"]),
                      trust={"A": 0.9, "B": 1.0}))
    healed = [a for a in evaluate_health_alerts(alerts, rec)
              if a.get("healed")]
    assert [a["what"] for a in healed] == ["trust_low"]
    # a critical fires at the rss threshold and gates until healed
    rec2 = h.feed(_ev("resource", "B", 8, 15.5, rss_gb=3.0,
                      cpu_percent=10.0))
    assert rec2 is None
    rec3 = h.feed(dict(_merge("B", 9, 16.0, version=4,
                              arrivals=[_arrival("A", 4)],
                              component=["A", "B"])))
    crit = [a for a in evaluate_health_alerts(alerts, rec3)
            if a["severity"] == CRITICAL]
    assert [a["what"] for a in crit] == ["rss_high"]
    assert alerts.unhealed(CRITICAL) != []


# ---------------------------------------------------------- live collator


def _write_stream(dirpath, peer, events):
    path = os.path.join(str(dirpath), f"events_peer{peer}.jsonl")
    with open(path, "wb") as f:
        for e in events:
            f.write(json.dumps(e).encode() + b"\n")
    return path


def test_live_collator_matches_batch_collate(tmp_path):
    for name, events, _firing in _fixtures():
        d = tmp_path / name
        d.mkdir()
        by_peer = {}
        for e in events:
            by_peer.setdefault(str(e["peer"]), []).append(e)
        paths = [_write_stream(d, p, evs) for p, evs in by_peer.items()]
        batch = T.collate(paths)
        lc = LiveCollator(str(d))
        summary = lc.finalize()
        assert summary["invariants"] == batch["invariants"], name
        assert summary["events"] == batch["timeline"]["events"], name
        assert summary["torn_tails"] == batch["torn_tails"], name
        assert summary["ok"] == batch["ok"] or not batch["ok"], name


def test_live_collator_picks_up_streams_mid_run(tmp_path):
    _write_stream(tmp_path, "A", [_send("A", 0, 10.0, to="B", msg_id=0)])
    lc = LiveCollator(str(tmp_path))
    lc.sweep()
    assert len(lc.tailers) == 1 and lc.events == 1
    # a second peer's stream appears after monitoring began
    _write_stream(tmp_path, "B", [_recv("B", 0, 10.2, src="A", msg_id=0),
                                  _end("B", 1, 20.0)])
    lc.sweep()
    assert len(lc.tailers) == 2 and lc.events == 3
    assert not lc.all_closed()      # A's stream never closed
    with open(os.path.join(str(tmp_path), "events_peerA.jsonl"),
              "ab") as f:
        f.write(json.dumps(_end("A", 1, 20.0)).encode() + b"\n")
    lc.sweep()
    assert lc.all_closed()
    assert lc.finalize()["ok"]


def test_live_collator_emits_health_and_alert_events(tmp_path):
    run = tmp_path / "run"
    run.mkdir()
    by_peer = {}
    for e in _soak_like_events():
        by_peer.setdefault(str(e["peer"]), []).append(e)
    for p, evs in by_peer.items():
        _write_stream(run, p, evs)
    health_path = str(tmp_path / "health.jsonl")
    T.install(T.EventWriter(health_path, run="monitor", flush_every=1))
    try:
        lc = LiveCollator(str(run),
                          thresholds=AlertThresholds(trust_warn=0.35))
        lc.finalize()
    finally:
        T.uninstall()
    events, meta = T.read_stream(health_path)
    assert meta["corrupt_lines"] == 0 and not meta["torn_tail"]
    kinds = {e["ev"] for e in events}
    assert kinds == {"health", "alert"}   # catalogued types only
    health = [e for e in events if e["ev"] == "health"]
    assert [h["round"] for h in health] == [1, 2]
    assert health[0]["trust"] == {"A": 0.9, "B": 1.0}
    alerts = [e for e in events if e["ev"] == "alert"]
    assert any(a["what"] == "trust_low" and a["severity"] == "warn"
               for a in alerts)


# -------------------------------------------------------------- monitor CLI


def test_monitor_cli_clean_run_exits_zero(tmp_path, capsys):
    run = tmp_path / "run"
    run.mkdir()
    by_peer = {}
    for e in tt._clean_run():
        by_peer.setdefault(str(e["peer"]), []).append(e)
    for p, evs in by_peer.items():
        _write_stream(run, p, evs)
    summary_path = str(tmp_path / "summary.json")
    rc = monitor_main([str(run), "--once", "--quiet",
                       "--summary-out", summary_path])
    assert rc == 0
    with open(summary_path) as f:
        summary = json.load(f)
    assert summary["ok"] and summary["invariant_violations_total"] == 0
    assert summary["health_records"] == 2
    assert os.path.exists(os.path.join(str(run), "health.jsonl"))
    # health.jsonl is outside the events_*.jsonl glob: trace never
    # ingests the observer's own stream
    assert os.path.join(str(run), "health.jsonl") not in \
        T.find_streams(str(run))


def test_monitor_cli_flags_seeded_violation_while_stream_open(tmp_path):
    """The chaos_smoke monitor-leg contract: a double-merge in a stream
    that has NOT closed (no run.end — the run is still alive) must exit
    nonzero."""
    run = tmp_path / "run"
    run.mkdir()
    events = tt._clean_run()
    events[3]["arrivals"] = [_arrival("A", 0)]   # the double merge
    events = [e for e in events if e["ev"] != "run.end"]  # still alive
    by_peer = {}
    for e in events:
        by_peer.setdefault(str(e["peer"]), []).append(e)
    for p, evs in by_peer.items():
        _write_stream(run, p, evs)
    rc = monitor_main([str(run), "--once", "--quiet",
                       "--health-out", "off"])
    assert rc == 1


def test_monitor_cli_no_streams_exits_two(tmp_path):
    rc = monitor_main([str(tmp_path), "--once", "--quiet",
                       "--health-out", "off"])
    assert rc == 2


# ------------------------------------------------- resource sampling mode


def test_resource_monitor_periodic_sampling(tmp_path):
    import time

    from bcfl_tpu.metrics import ResourceMonitor

    path = str(tmp_path / "events_rs.jsonl")
    T.install(T.EventWriter(path, peer=7, run="rs", flush_every=1))
    try:
        m = ResourceMonitor()
        assert m.start_sampling(0.02)
        assert not m.start_sampling(0.02)   # idempotent while running
        time.sleep(0.15)
        m.stop_sampling()
        m.stop_sampling()                   # idempotent when stopped
    finally:
        T.uninstall()
    events, meta = T.read_stream(path)
    samples = [e for e in events if e["ev"] == "resource"]
    assert len(samples) >= 2                # actually periodic
    assert meta["corrupt_lines"] == 0
    for s in samples:
        assert s["rss_gb"] > 0 and s["cpu_percent"] >= 0
        assert s["peer"] == 7               # rides the process stream
    # the health series picks the samples up
    h = HealthRollup()
    for s in samples:
        h.feed(s)
    rec = h.feed(_merge("B", 0, 10.0, version=1,
                        arrivals=[_arrival("A", 0)]))
    assert rec["resource"]["7"]["rss_gb"] > 0
