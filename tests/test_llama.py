"""Llama decoder family: shapes, causal masking, GQA, RoPE, LoRA targets,
tensor-parallel specs, and an end-to-end federated LoRA run."""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from bcfl_tpu.config import FedConfig, PartitionConfig
from bcfl_tpu.fed.engine import FedEngine
from bcfl_tpu.models import build, get_config, list_models, lora_targets
from bcfl_tpu.models import lora as lora_lib
from bcfl_tpu.models.llama import LORA_TARGETS, causal_bias, rope, tp_specs

import pytest

pytestmark = pytest.mark.slow  # engine-suite tier: compile-heavy on the
# 8-device CPU mesh; the tier-1 'not slow' window runs the chaos matrix
# (tests/test_faults.py) as its fast engine coverage instead


def _init(model, B=2, S=16):
    ids = jnp.ones((B, S), jnp.int32)
    return model.init(jax.random.key(0), ids, ids)["params"]


def test_registry():
    assert "tiny-llama" in list_models() and "llama2-7b" in list_models()
    cfg = get_config("llama2-7b")
    assert cfg.hidden_size == 4096 and cfg.num_layers == 32
    assert lora_targets("tiny-llama") == LORA_TARGETS


def test_forward_shapes_and_padding():
    model = build("tiny-llama", num_labels=3)
    params = _init(model)
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 8192, (2, 16)),
                      jnp.int32)
    mask = jnp.ones((2, 16), jnp.int32).at[1, 8:].set(0)
    logits = model.apply({"params": params}, ids, mask)
    assert logits.shape == (2, 3)
    assert logits.dtype == jnp.float32
    # padding tokens must not affect the pooled logits: changing pad ids is a no-op
    ids2 = ids.at[1, 8:].set(7)
    logits2 = model.apply({"params": params}, ids2, mask)
    np.testing.assert_allclose(np.asarray(logits[1]), np.asarray(logits2[1]),
                               atol=1e-5)


def test_causal_bias():
    mask = jnp.ones((1, 4), jnp.int32).at[0, 3:].set(0)
    b = causal_bias(mask)
    assert b.shape == (1, 1, 4, 4)
    bm = np.asarray(b[0, 0])
    assert bm[0, 1] < -1e20  # future masked
    assert bm[2, 0] == 0.0  # past visible
    assert bm[1, 3] < -1e20  # padded key masked


def test_rope_relative_shift():
    # RoPE inner products depend only on relative positions
    D = 8
    x = jax.random.normal(jax.random.key(0), (1, 1, 2, D), jnp.float32)
    p0 = jnp.asarray([0.0, 5.0])
    p1 = jnp.asarray([3.0, 8.0])  # same relative offset
    r0 = rope(x, p0, 10000.0)[0, 0]
    r1 = rope(x, p1, 10000.0)[0, 0]
    d0 = float(r0[0] @ r0[1])
    d1 = float(r1[0] @ r1[1])
    assert abs(d0 - d1) < 1e-4


def test_lora_on_llama():
    model = build("tiny-llama", num_labels=2)
    params = _init(model)
    adapters = lora_lib.init_lora(jax.random.key(1), params, rank=4,
                                  targets=LORA_TARGETS)
    # every decoder layer contributes all 7 projection kernels, plus the
    # classifier head stored whole (full-trained under LoRA)
    assert len(adapters) == 2 * 7 + 1
    assert any("classifier" in k for k in adapters)
    merged = lora_lib.apply_lora(params, adapters)
    # b=0 init + untouched head copies -> merge is identity
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(merged)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b))


def test_tp_specs_shapes():
    model = build("tiny-llama", num_labels=2)
    params = _init(model)
    specs = tp_specs(params)
    flat = jax.tree_util.tree_flatten_with_path(specs)[0]
    by_name = {}
    for path, s in flat:
        names = tuple(getattr(p, "key", str(p)) for p in path)
        if len(names) >= 2:
            by_name[names[-2]] = s
    assert by_name["q_proj"] == P(None, "tp", None)
    assert by_name["gate_proj"] == P(None, "tp")
    assert by_name["o_proj"] == P("tp", None, None)
    assert by_name["down_proj"] == P("tp", None)


def test_federated_llama_lora_run():
    cfg = FedConfig(
        name="llama-smoke", model="tiny-llama", dataset="synthetic",
        num_labels=2, mode="serverless", weighted_agg=False,
        num_clients=4, num_rounds=2, seq_len=32, max_local_batches=2,
        batch_size=8, lora_rank=4,
        partition=PartitionConfig(kind="iid", iid_samples=32),
    )
    res = FedEngine(cfg).run()
    assert len(res.metrics.rounds) == 2
    assert res.metrics.rounds[-1].global_acc is not None
    # only adapters travel: aggregated payload is much smaller than the model
    from bcfl_tpu.metrics import model_size_gb

    assert model_size_gb(res.trainable) < 0.25 * model_size_gb(res.params)


def test_flash_path_matches_dense_path():
    # same params, same inputs: flash (blockwise causal) vs dense bias path
    import dataclasses

    from bcfl_tpu.models.llama import LlamaClassifier

    cfg_dense = get_config("tiny-llama", num_labels=2, use_flash=False)
    cfg_flash = dataclasses.replace(cfg_dense, use_flash=True, flash_min_seq=0)
    m_dense, m_flash = LlamaClassifier(cfg_dense), LlamaClassifier(cfg_flash)
    params = _init(m_dense, B=2, S=64)
    rng = np.random.default_rng(3)
    ids = jnp.asarray(rng.integers(0, 8192, (2, 64)), jnp.int32)
    mask = jnp.ones((2, 64), jnp.int32).at[1, 40:].set(0)
    ld = m_dense.apply({"params": params}, ids, mask)
    lf = m_flash.apply({"params": params}, ids, mask)
    np.testing.assert_allclose(np.asarray(ld), np.asarray(lf), atol=2e-2)


# ----------------------------- federated causal LM --------------------------

def test_engine_causal_lm_learns():
    """task='causal_lm': federated next-token fine-tuning of the decoder —
    the capability the repo title promises beyond the reference's
    classification-only task. Loss must drop over rounds on a repetitive
    synthetic corpus."""
    from bcfl_tpu.config import FedConfig, PartitionConfig
    from bcfl_tpu.fed.engine import FedEngine

    cfg = FedConfig(
        task="causal_lm", dataset="synthetic", num_labels=2, seq_len=32,
        batch_size=8, vocab_size=256, model="tiny-llama", num_clients=4,
        num_rounds=3, learning_rate=3e-3, max_local_batches=4,
        partition=PartitionConfig(kind="iid", iid_samples=32))
    res = FedEngine(cfg).run()
    losses = [r.train_loss for r in res.metrics.rounds]
    assert len(losses) == 3
    assert losses[-1] < losses[0] * 0.9, losses
    # global eval uses per-token normalization too
    assert res.metrics.global_accuracies[-1] > 0.0


def test_engine_causal_lm_with_tp_lora():
    """causal_lm composes with clients x tp LoRA on the 2-D mesh — and the
    adapters can actually move the LM loss (regression: lm_head used to be
    absent from LORA_TARGETS, so LoRA optimized against a frozen random
    vocab projection)."""
    from bcfl_tpu.config import FedConfig, PartitionConfig
    from bcfl_tpu.fed.engine import FedEngine

    cfg = FedConfig(
        task="causal_lm", dataset="synthetic", num_labels=2, seq_len=16,
        batch_size=8, vocab_size=256, model="tiny-llama", lora_rank=4, tp=2,
        num_clients=4, num_rounds=3, learning_rate=5e-3, max_local_batches=4,
        partition=PartitionConfig(kind="iid", iid_samples=32))
    eng = FedEngine(cfg)
    # lm_head carries a LoRA adapter (not a frozen random projection)
    assert any("lm_head" in k for k in eng.trainable0)
    res = eng.run()
    losses = [r.train_loss for r in res.metrics.rounds]
    assert losses[-1] < losses[0], losses


def test_causal_lm_rejects_encoders():
    import pytest

    from bcfl_tpu.models import build

    with pytest.raises(ValueError, match="encoder"):
        build("tiny-bert", head="lm")
