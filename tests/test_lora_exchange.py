"""Adapter-only federated exchange (COMPRESSION.md "Adapter exchange").

Config-level: ``lora_ranks`` spec parsing + canonicalization (``lora_rank``
becomes the cohort max), the heterogeneous-rank composition rejections
(robust aggregators / gossip / faithful / registry / dist / shard_map), and
the capability-table rows for adapter exchange.
Math-level: the static rank mask, per-client adapter clipping, the
rank-aware RBLA weighted mean (padded coordinates excluded per rank dim,
per-dim fallback when every contributor is padding), and the Shannon
effective-rank statistic.
Engine-level: a heterogeneous fleet trains under RBLA with the effective
rank recorded every round and ZERO per-round retraces; LoRA composes with
int8+topk error feedback bit-identically across crash/resume (adapter + EF
residual ride the checkpoint); resuming under a different rank layout is
refused loudly.
Dist-level (marker ``dist``): a real 2-peer loopback run ships ONLY
adapter-scale update frames, with ledger authentication over the adapter
payloads and robust merge votes on the flattened adapter vectors.

The whole file is fast/`not slow`, so tier-1 runs it.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bcfl_tpu.compression import CompressionConfig, payload_nbytes
from bcfl_tpu.config import (
    DistConfig,
    FedConfig,
    LedgerConfig,
    PartitionConfig,
    capability_table,
    parse_lora_ranks,
)
from bcfl_tpu.faults import FaultPlan, SimulatedCrash
from bcfl_tpu.fed.engine import FedEngine
from bcfl_tpu.models import lora as lora_lib
from bcfl_tpu.parallel import gspmd

INT8_TOPK = CompressionConfig(kind="int8+topk", topk_frac=0.1)


def _tiny(**kw):
    base = dict(
        dataset="synthetic", model="tiny-bert", num_clients=4, num_rounds=2,
        seq_len=16, batch_size=4, max_local_batches=2, vocab_size=512,
        eval_every=0,
        partition=PartitionConfig(kind="iid", iid_samples=8),
    )
    base.update(kw)
    return FedConfig(**base)


# ------------------------------------------------------------------- config


def test_parse_lora_ranks():
    assert parse_lora_ranks("2,4,8") == (2, 4, 8)
    assert parse_lora_ranks("16") == (16,)
    for bad in ("", "2,x", "2,,4", "0", "2,-4"):
        with pytest.raises(ValueError, match="lora_ranks"):
            parse_lora_ranks(bad)


def test_lora_ranks_canonicalization():
    cfg = _tiny(lora_ranks="2,4")
    # lora_rank canonicalizes to the cohort max, so every existing
    # `lora_rank > 0` switch sees the padded ceiling
    assert cfg.lora_rank == 4
    assert cfg.lora_rank_spec == (2, 4)
    # the spec cycles over the stacked client axis
    assert cfg.client_lora_ranks == (2, 4, 2, 4)
    assert _tiny(lora_ranks="2,4,8").client_lora_ranks == (2, 4, 8, 2)
    # uniform fleets report no spec at all (shared program-cache entry)
    assert _tiny(lora_rank=4).client_lora_ranks is None
    assert _tiny().lora_rank_spec is None
    with pytest.raises(ValueError, match="not both"):
        _tiny(lora_ranks="2,4", lora_rank=2)


@pytest.mark.parametrize("kw,needle", [
    (dict(aggregator="trimmed_mean"), "structural zero padding"),
    (dict(aggregator="median"), "structural zero padding"),
    (dict(mode="serverless"), "mode='server'"),
    (dict(faithful=True), "faithful"),
    (dict(registry_size=100, sample_clients=4), "registry"),
])
def test_hetero_composition_rejections(kw, needle):
    with pytest.raises(ValueError, match=needle):
        _tiny(lora_ranks="2,4", **kw)
    # a UNIFORM spec ("4,4" = everyone at 4) is not heterogeneous: the
    # combination constructs wherever plain lora_rank=4 would
    if "registry" not in kw and "mode" not in kw and "faithful" not in kw:
        _tiny(lora_ranks="4,4", **kw)


def test_hetero_rejected_on_dist_via_caps_table():
    with pytest.raises(ValueError, match="not supported on runtime='dist'"):
        FedConfig(runtime="dist", sync="async", eval_every=0, num_clients=4,
                  lora_ranks="2,4", dist=DistConfig(peers=2))
    try:
        FedConfig(runtime="dist", sync="async", eval_every=0, num_clients=4,
                  lora_ranks="2,4", dist=DistConfig(peers=2))
    except ValueError as e:
        assert "uniform lora_rank" in str(e)
    # ... while UNIFORM adapter exchange is a declared dist capability
    cfg = FedConfig(runtime="dist", sync="async", eval_every=0,
                    num_clients=4, lora_rank=2, dist=DistConfig(peers=2))
    rows = {f: v for f, _, v in capability_table(cfg)}
    assert rows["LoRA adapter exchange"] is True


def test_shard_map_impl_rejects_hetero():
    from bcfl_tpu.core.mesh import client_mesh
    from bcfl_tpu.fed.client_step import build_programs
    from bcfl_tpu.models import build

    model = build("tiny-bert", num_labels=2, vocab_size=512)
    with pytest.raises(ValueError, match="gspmd"):
        build_programs(model, client_mesh(4), impl="shard_map",
                       lora_ranks=(2, 4, 2, 4))
    # a uniform tuple normalizes onto the PLAIN program set — identical
    # object, so shard_map (and every cache hit) keeps working
    a = build_programs(model, client_mesh(4))
    b = build_programs(model, client_mesh(4), lora_ranks=(4, 4, 4, 4))
    assert b is a


# --------------------------------------------------------------- rank math


def test_rank_mask_and_clip_adapters():
    m = lora_lib.rank_mask((2, 4, 1))
    np.testing.assert_array_equal(
        np.asarray(m),
        [[1, 1, 0, 0], [1, 1, 1, 1], [1, 0, 0, 0]])

    adapters = {"enc": {"a": jnp.ones((3, 4)), "b": jnp.ones((4, 5))},
                "head": {"full": jnp.ones((2,))}}
    out = lora_lib.clip_adapters(adapters, m[0])
    np.testing.assert_array_equal(
        np.asarray(out["enc"]["a"]),
        np.concatenate([np.ones((3, 2)), np.zeros((3, 2))], axis=1))
    np.testing.assert_array_equal(
        np.asarray(out["enc"]["b"]),
        np.concatenate([np.ones((2, 5)), np.zeros((2, 5))], axis=0))
    # head leaves are full-tensor (not rank-structured): untouched
    np.testing.assert_array_equal(np.asarray(out["head"]["full"]),
                                  np.ones((2,)))


def test_rank_aware_weighted_mean_excludes_padding():
    # client 0 at rank 1 (dim 1 is padding), client 1 at rank 2
    mask = lora_lib.rank_mask((1, 2))
    a = jnp.stack([jnp.full((3, 2), 2.0), jnp.full((3, 2), 6.0)])
    b = jnp.stack([jnp.full((2, 5), 2.0), jnp.full((2, 5), 6.0)])
    full = jnp.stack([jnp.full((4,), 2.0), jnp.full((4,), 6.0)])
    tree = {"m": {"a": a, "b": b}, "h": {"full": full}}
    w = jnp.asarray([1.0, 3.0])
    out = gspmd.rank_aware_weighted_mean(tree, w, mask)
    # dim 0: both contribute -> (1*2 + 3*6)/4 = 5; dim 1: only client 1
    np.testing.assert_allclose(np.asarray(out["m"]["a"][:, 0]), 5.0)
    np.testing.assert_allclose(np.asarray(out["m"]["a"][:, 1]), 6.0)
    np.testing.assert_allclose(np.asarray(out["m"]["b"][0]), 5.0)
    np.testing.assert_allclose(np.asarray(out["m"]["b"][1]), 6.0)
    # 'full' leaves (task heads) take the PLAIN weighted mean
    np.testing.assert_allclose(np.asarray(out["h"]["full"]), 5.0)

    # zero-weight round: every dim falls back
    fb = {"m": {"a": jnp.full((3, 2), 9.0), "b": jnp.full((2, 5), 9.0)},
          "h": {"full": jnp.full((4,), 9.0)}}
    out0 = gspmd.rank_aware_weighted_mean(
        tree, jnp.zeros((2,)), mask, fallback=fb)
    for leaf in jax.tree.leaves(out0):
        np.testing.assert_allclose(np.asarray(leaf), 9.0)

    # PARTIAL fallback: with only the rank-1 client weighted, dim 1 has no
    # live contributor -> that dim alone reverts to the fallback
    out1 = gspmd.rank_aware_weighted_mean(
        tree, jnp.asarray([1.0, 0.0]), mask, fallback=fb)
    np.testing.assert_allclose(np.asarray(out1["m"]["a"][:, 0]), 2.0)
    np.testing.assert_allclose(np.asarray(out1["m"]["a"][:, 1]), 9.0)
    np.testing.assert_allclose(np.asarray(out1["m"]["b"][0]), 2.0)
    np.testing.assert_allclose(np.asarray(out1["m"]["b"][1]), 9.0)


def test_effective_rank_statistic():
    # R orthogonal equal-energy factor pairs -> effective rank == R
    adapters = {"m": {"a": jnp.eye(4), "b": 2.0 * jnp.eye(4)}}
    np.testing.assert_allclose(
        float(lora_lib.effective_rank(adapters)), 4.0, rtol=1e-5)
    # all energy in ONE dim -> 1.0 (the collapse signature)
    one = {"m": {"a": jnp.eye(4) * jnp.asarray([1.0, 0, 0, 0]),
                 "b": jnp.eye(4)}}
    np.testing.assert_allclose(
        float(lora_lib.effective_rank(one)), 1.0, rtol=1e-5)
    # zero adapters (b starts at zero) and head-only trees report 0.0
    zero = {"m": {"a": jnp.eye(4), "b": jnp.zeros((4, 4))}}
    assert float(lora_lib.effective_rank(zero)) == 0.0
    assert float(lora_lib.effective_rank({"h": {"full": jnp.ones(3)}})) == 0.0


# ------------------------------------------------------------------- engine


def test_hetero_engine_records_effective_rank_zero_retraces():
    eng = FedEngine(_tiny(lora_ranks="2,4"))
    res = eng.run()
    assert len(res.metrics.rounds) == 2
    for rec in res.metrics.rounds:
        # the rank-collapse guard: recorded every round, in (0, n_dims]
        assert rec.effective_rank is not None
        assert 0.0 < rec.effective_rank
    # the padding mask is static -> the round program compiled exactly once
    assert eng.progs.server_round._cache_size() == 1
    # bytes accounting is adapter-sized: the wire carries the adapter tree,
    # not the merged full model
    rec = res.metrics.rounds[0]
    assert rec.bytes_on_wire == payload_nbytes(None, res.trainable) * 4
    assert rec.bytes_on_wire < payload_nbytes(None, res.params)


def test_lora_compress_ef_crash_resume_bit_identical(tmp_path):
    """LoRA x int8+topk x error feedback: the checkpoint carries the
    adapter tree AND the adapter-shaped EF residual, so crash + resume
    reproduces the uninterrupted compressed run bit-for-bit — the pinned
    composition for `--lora-rank` + `--compress` + EF."""
    kw = dict(lora_rank=2, compression=INT8_TOPK, num_rounds=3,
              checkpoint_every=1)
    ref = FedEngine(_tiny(**kw)).run()
    cfg = _tiny(checkpoint_dir=str(tmp_path),
                faults=FaultPlan(crash_at_round=1), **kw)
    with pytest.raises(SimulatedCrash):
        FedEngine(cfg).run()
    res = FedEngine(cfg).run(resume=True)
    for a, b in zip(jax.tree.leaves(ref.trainable),
                    jax.tree.leaves(res.trainable)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # the compressed exchange shipped adapter-scale payloads
    assert (res.metrics.rounds[-1].bytes_on_wire
            < res.metrics.rounds[-1].bytes_raw)

    # the checkpoint records the rank layout: resuming under a different
    # one would reinterpret the adapter/EF trees — refused loudly (same
    # guard class as the wire-format and prng-impl resume checks)
    with pytest.raises(ValueError, match="rank layout"):
        FedEngine(cfg.replace(lora_rank=4)).run(resume=True)
    with pytest.raises(ValueError, match="rank layout"):
        FedEngine(cfg.replace(lora_rank=0, lora_ranks="2,4")).run(
            resume=True)


def test_cli_lora_ranks_flag_fails_fast_on_bad_combos():
    """`--lora-ranks` reaches FedConfig, whose validation fires at CONFIG
    time — the CLI exits with the clear message before any engine work."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ)
    site_pkgs = [p for p in sys.path if p.endswith("site-packages")]
    env["PYTHONPATH"] = os.pathsep.join([repo] + site_pkgs)
    env["JAX_PLATFORMS"] = "cpu"

    def cli(*flags):
        return subprocess.run(
            [sys.executable, "-S", "-m", "bcfl_tpu.entrypoints",
             "--preset", "smoke", *flags],
            capture_output=True, text=True, timeout=120, env=env, cwd=repo)

    out = cli("--lora-ranks", "2,4", "--lora-rank", "2")
    assert out.returncode != 0
    assert "not both" in out.stderr + out.stdout
    out = cli("--lora-ranks", "2,x")
    assert out.returncode != 0
    assert "comma-separated positive ints" in out.stderr + out.stdout


# --------------------------------------------------------------------- dist


@pytest.mark.dist
def test_dist_loopback_lora_adapter_exchange(tmp_path):
    """Adapters on the real wire: a 3-peer loopback federation with
    lora_rank=2 completes with every update frame at adapter scale (the
    ~12 MB full-model frame never crosses the socket), ledger replicas
    authenticating the adapter payloads on every peer, robust merge votes
    (trimmed mean needs a >= 3-deep buffer, hence 3 peers) over the
    flattened adapter vectors, and zero telemetry-invariant violations."""
    from bcfl_tpu.dist.harness import run_dist
    from bcfl_tpu.telemetry import collate_run

    peers = (0, 1, 2)
    cfg = FedConfig(
        name="dist_lora_smoke", runtime="dist", mode="server", sync="async",
        model="tiny-bert", dataset="synthetic", num_clients=6, num_rounds=3,
        seq_len=16, batch_size=4, max_local_batches=2, eval_every=0,
        lora_rank=2, aggregator="trimmed_mean",
        partition=PartitionConfig(kind="iid", iid_samples=8),
        ledger=LedgerConfig(enabled=True),
        dist=DistConfig(peers=3, buffer=3, buffer_timeout_s=5.0,
                        idle_timeout_s=120.0, peer_deadline_s=220.0,
                        checkpoint_every_versions=0))
    result = run_dist(cfg, str(tmp_path / "run"), deadline_s=240.0,
                      platform="cpu")
    assert result["returncodes"] == {"0": 0, "1": 0, "2": 0}, \
        result["log_tails"]
    assert result["ok"], result["log_tails"]
    reports = result["reports"]
    assert all(reports[p]["final_version"] >= cfg.num_rounds for p in peers)
    # ledger auth over adapter payloads: every chain replica verifies and
    # all replicas agree
    assert all(reports[p]["chain_ok"] for p in peers)
    assert len({reports[p]["chain_head"] for p in peers}) == 1

    col = collate_run(result["run_dir"])
    assert col["ok"], col["violations"]
    frames = [e["bytes"] for e in col["ordered"]
              if e["ev"] == "send" and e.get("ok")
              and e.get("type") == "update"]
    assert frames, "no update frames observed"
    # adapter-scale: rank-2 tiny-bert updates measure ~210 KB for a
    # 2-client slice vs ~12 MB full-model (scripts/lora_comm.py records
    # the measured ratio); 1 MB is an order-of-magnitude-safe ceiling
    assert max(frames) < 1_000_000, max(frames)
