import jax
import numpy as np

from bcfl_tpu.core import client_mesh, client_round_keys


def test_mesh_divisor_layouts():
    # 8 CPU devices forced by conftest
    assert len(jax.devices()) == 8
    m = client_mesh(8)
    assert m.n_devices == 8 and m.per_device == 1
    m = client_mesh(10)  # 10 clients on 8 devices -> 5 devices x 2 stacked
    assert m.n_devices == 5 and m.per_device == 2
    m = client_mesh(3)
    assert m.n_devices == 3 and m.per_device == 1
    m = client_mesh(16)
    assert m.n_devices == 8 and m.per_device == 2


def test_shard_clients_places_leading_dim():
    m = client_mesh(8)
    x = np.arange(8 * 4, dtype=np.float32).reshape(8, 4)
    xs = m.shard_clients(x)
    assert xs.sharding.spec == jax.sharding.PartitionSpec("clients")


def test_client_round_keys_distinct():
    keys = client_round_keys(jax.random.key(0), 4, round_idx=0)
    assert keys.shape[0] == 4
    flat = np.asarray(jax.random.key_data(keys)).reshape(4, -1)
    assert len({tuple(r) for r in flat.tolist()}) == 4
    keys2 = client_round_keys(jax.random.key(0), 4, round_idx=1)
    assert not np.array_equal(
        np.asarray(jax.random.key_data(keys)), np.asarray(jax.random.key_data(keys2))
    )
