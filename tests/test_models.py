import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bcfl_tpu.models import build, get_config, list_models
from bcfl_tpu.models.bert import TextClassifier
from bcfl_tpu.models import lora


def _init_and_apply(name, B=2, L=16):
    model = build(name, num_labels=3)
    ids = jnp.ones((B, L), jnp.int32)
    mask = jnp.ones((B, L), jnp.int32)
    params = model.init(jax.random.key(0), ids, mask)
    logits = model.apply(params, ids, mask)
    return model, params, logits


@pytest.mark.parametrize("name", ["tiny-bert", "tiny-albert"])
def test_forward_shapes(name):
    _, _, logits = _init_and_apply(name)
    assert logits.shape == (2, 3)
    assert logits.dtype == jnp.float32
    assert np.isfinite(np.asarray(logits)).all()


def test_albert_shares_parameters():
    b = build("tiny-bert").init(jax.random.key(0), jnp.ones((1, 8), jnp.int32),
                                jnp.ones((1, 8), jnp.int32))
    a = build("tiny-albert").init(jax.random.key(0), jnp.ones((1, 8), jnp.int32),
                                  jnp.ones((1, 8), jnp.int32))
    nb = sum(x.size for x in jax.tree.leaves(b))
    na = sum(x.size for x in jax.tree.leaves(a))
    assert na < nb  # shared layer + factorized embedding


def test_padding_mask_invariance():
    """Logits must not depend on token content in padded positions."""
    model = build("tiny-bert")
    ids = jnp.array([[2, 10, 11, 3, 0, 0, 0, 0]], jnp.int32)
    mask = jnp.array([[1, 1, 1, 1, 0, 0, 0, 0]], jnp.int32)
    params = model.init(jax.random.key(0), ids, mask)
    l1 = model.apply(params, ids, mask)
    ids2 = ids.at[0, 5].set(999)
    l2 = model.apply(params, ids2, mask)
    np.testing.assert_allclose(np.asarray(l1), np.asarray(l2), atol=1e-5)


def test_jit_forward_compiles_once():
    model = build("tiny-bert")
    ids = jnp.ones((4, 16), jnp.int32)
    mask = jnp.ones((4, 16), jnp.int32)
    params = model.init(jax.random.key(0), ids, mask)
    f = jax.jit(lambda p, i, m: model.apply(p, i, m))
    out1 = f(params, ids, mask)
    out2 = f(params, ids + 1, mask)
    assert out1.shape == out2.shape == (4, 2)


def test_registry():
    assert {"tiny-bert", "bert-base", "albert-base", "biobert-base"} <= set(list_models())
    with pytest.raises(KeyError):
        get_config("nope")


def test_lora_identity_at_init_then_trains():
    model = build("tiny-bert")
    ids = jnp.ones((2, 8), jnp.int32)
    mask = jnp.ones((2, 8), jnp.int32)
    variables = model.init(jax.random.key(0), ids, mask)
    adapters = lora.init_lora(jax.random.key(1), variables["params"], rank=4)
    assert len(adapters) > 0
    merged = lora.apply_lora(variables["params"], adapters)
    l0 = model.apply(variables, ids, mask)
    l1 = model.apply({"params": merged}, ids, mask)
    np.testing.assert_allclose(np.asarray(l0), np.asarray(l1), atol=1e-6)
    # perturb b -> output changes (head entries store the leaf whole)
    for k in adapters:
        if "b" in adapters[k]:
            adapters[k]["b"] = adapters[k]["b"] + 0.1
    l2 = model.apply({"params": lora.apply_lora(variables["params"], adapters)}, ids, mask)
    assert np.abs(np.asarray(l2) - np.asarray(l0)).max() > 1e-4
    # adapters are much smaller than the base
    assert lora.num_params(adapters) < 0.2 * lora.num_params(variables["params"])
