"""Bit-for-bit parity of the C++ hash-tokenizer core against the Python
reference path (`HashTokenizer.encode`), which itself is pinned by
tests/test_data.py. The native path must agree on EVERY byte of ids+mask —
a silent divergence would re-tokenize every dataset differently depending on
whether a toolchain is present."""

import numpy as np
import pytest

from bcfl_tpu.data.tokenizer import HashTokenizer
from bcfl_tpu.native.build import load_tokenizer_lib

pytestmark = pytest.mark.skipif(
    load_tokenizer_lib() is None, reason="no C++ toolchain")

TRICKY = [
    "",
    " ",
    "the quick brown fox",
    "The QUICK Brown FOX!!",
    "don't stop-me now; it's 2024...",
    "  leading and   trailing   ",
    "tabs\tnewlines\nand\r\nmore",
    "unicode éÉ ß İ straße",  # ß lowers to ß; İ -> i̇ (2 cp)
    "cjk 世界 and emoji \U0001f600\U0001f680",
    "unicode spaces a b c d　e",
    "mixed: café-naïve 'quoted' (parens) [brackets]",
    "digits 0123456789 and '''apostrophes'''",
    "ẞ",  # LATIN CAPITAL SHARP S lowers to U+00DF
    "x" * 5000,  # single huge word
    ("word " * 600).strip(),  # long doc, exercises the early-exit cap
]


def _python_batch(tok, texts, seq_len):
    ids = np.empty((len(texts), seq_len), dtype=np.int32)
    mask = np.empty((len(texts), seq_len), dtype=np.int32)
    for i, t in enumerate(texts):
        ids[i], mask[i] = tok.encode(t, seq_len)
    return ids, mask


@pytest.mark.parametrize("seq_len", [1, 2, 3, 16, 128])
@pytest.mark.parametrize("vocab", [5, 8192, 30522])
def test_parity_tricky(seq_len, vocab):
    tok = HashTokenizer(vocab)
    got = tok._encode_batch_native(TRICKY, seq_len)
    assert got is not None
    want = _python_batch(tok, TRICKY, seq_len)
    np.testing.assert_array_equal(got[0], want[0])
    np.testing.assert_array_equal(got[1], want[1])


def test_parity_fuzz():
    rng = np.random.default_rng(0)
    # random codepoints incl. multibyte planes, whitespace-heavy, ASCII
    pools = [
        list(range(0x20, 0x7F)),
        [0x09, 0x0A, 0x20, 0xA0, 0x2003, 0x2028, 0x3000],
        list(range(0x3B1, 0x3CA)) + list(range(0x4E00, 0x4E20)),
        [0x1F600, 0x1F680, 0x10348],
    ]
    texts = []
    for _ in range(200):
        cps = []
        for _ in range(int(rng.integers(0, 80))):
            pool = pools[int(rng.integers(0, len(pools)))]
            cps.append(chr(pool[int(rng.integers(0, len(pool)))]))
        texts.append("".join(cps))
    tok = HashTokenizer(512)
    got = tok._encode_batch_native(texts, 32)
    want = _python_batch(tok, texts, 32)
    np.testing.assert_array_equal(got[0], want[0])
    np.testing.assert_array_equal(got[1], want[1])


def test_lone_surrogate_falls_back_to_python_semantics():
    """Texts with lone surrogates (errors='surrogateescape' reads) can't
    cross the UTF-8 ctypes boundary: the native path must decline (return
    None) so encode_batch behaves exactly like the Python path regardless
    of toolchain — which tokenizes fine when the surrogate word lies beyond
    the seq_len-2 cap."""
    tok = HashTokenizer(512)
    beyond_cap = "a b c d e f " + "\udcff"  # cap for seq_len=4 is 2 words
    assert tok._encode_batch_native([beyond_cap], 4) is None
    ids, mask = tok.encode_batch([beyond_cap, "plain"], 4)
    want = _python_batch(tok, [beyond_cap, "plain"], 4)
    np.testing.assert_array_equal(ids, want[0])
    np.testing.assert_array_equal(mask, want[1])


def test_encode_batch_uses_native_and_agrees():
    tok = HashTokenizer(8192)
    ids, mask = tok.encode_batch(TRICKY, 64)
    want = _python_batch(tok, TRICKY, 64)
    np.testing.assert_array_equal(ids, want[0])
    np.testing.assert_array_equal(mask, want[1])
