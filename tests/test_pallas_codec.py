"""Pallas codec kernels + kernel harness contracts (PERF.md "Custom
kernels").

The codec's Pallas kernels (``bcfl_tpu.ops.pallas_codec``) run here in
interpret mode on CPU — the exact kernel bodies, off silicon — and are
held to their declared parity: **bit-identical** payloads against the
per-leaf XLA reference encode, for every codec kind, stochastic and
deterministic, across padded / odd-width / rank-2-adapter shapes.

Both sides of every parity check are jitted: XLA:CPU strength-reduces
``x / 127.0`` differently under jit than in eager (reciprocal-multiply vs
IEEE divide, a 1-ULP scale difference), so bit-identity is defined — and
production-relevant — within a compilation context. Round programs are
always jitted; a receiver authenticates the bytes it received and never
re-encodes, so cross-program identity is not a wire requirement.

Harness contracts ride along: unknown ops reject loudly, ``kernel_impl``
never reaches the wire format (resume may switch impls freely), the
VMEM-budget decline degrades to the reference invisibly, and the
interpret knob honors ``BCFL_PALLAS_INTERPRET`` with the old flash var as
a deprecated alias.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from bcfl_tpu.compression import (
    CompressionConfig,
    decode_tree,
    encode_tree,
    wire_format,
)
from bcfl_tpu.compression.codecs import encode_tree_unfused
from bcfl_tpu.config import FedConfig, PartitionConfig
from bcfl_tpu.fed.engine import FedEngine
from bcfl_tpu.ops import pallas_codec, registry

pytestmark = pytest.mark.compression


def _tree(seed=0):
    """Stacked [C=4, ...] leaves: chunk-padded odd widths, a bf16-typical
    small vector, an exact-chunk-multiple leaf, and a rank-2 LoRA adapter
    pair (in_features x r and r x out_features views, COMPRESSION.md) —
    ties, zeros, and -0.0 included so tie-breaking and sign-preserving
    select are exercised."""
    k = jax.random.key(seed)
    t = {
        "w": jax.random.normal(jax.random.fold_in(k, 1), (4, 37, 5)) * 3.0,
        "b": jax.random.normal(jax.random.fold_in(k, 2), (4, 9)),
        "exact": jax.random.normal(jax.random.fold_in(k, 3), (4, 64)),
        "lora_a": jax.random.normal(jax.random.fold_in(k, 4), (4, 48, 2)),
        "lora_b": jax.random.normal(jax.random.fold_in(k, 5), (4, 2, 48)),
    }
    w = np.array(t["w"])
    w[0, 0, :4] = [0.5, 0.5, -0.5, 0.0]  # magnitude ties + an exact zero
    w[1, 0, :2] = [-0.0, 0.0]            # signed zeros survive the select
    t["w"] = jnp.asarray(w)
    return t


def _jit_encode(fn, comp):
    return jax.jit(lambda d, k: fn(comp, d, k))


@pytest.mark.parametrize("kind", ["int8", "topk", "int8+topk"])
@pytest.mark.parametrize("stochastic", [False, True])
def test_pallas_encode_bit_identical(kind, stochastic):
    """kernel_impl="pallas" (interpret mode here) must produce payloads
    BIT-identical to the per-leaf pure-XLA reference encode — same dtypes,
    same bits, so ledger digests and checkpointed EF state cannot move
    with impl selection."""
    ref_comp = CompressionConfig(kind=kind, chunk=16, topk_frac=0.3,
                                 stochastic=stochastic)
    pl_comp = CompressionConfig(kind=kind, chunk=16, topk_frac=0.3,
                                stochastic=stochastic, kernel_impl="pallas")
    tree, key = _tree(), jax.random.key(7)
    a = _jit_encode(encode_tree_unfused, ref_comp)(tree, key)
    b = _jit_encode(encode_tree, pl_comp)(tree, key)
    assert (jax.tree_util.tree_structure(a)
            == jax.tree_util.tree_structure(b))
    for (pa, xa), (_, xb) in zip(
            jax.tree_util.tree_flatten_with_path(a)[0],
            jax.tree_util.tree_flatten_with_path(b)[0]):
        assert np.asarray(xa).dtype == np.asarray(xb).dtype, pa
        np.testing.assert_array_equal(np.asarray(xa), np.asarray(xb),
                                      err_msg=str(pa))
    # and every impl decodes the same payload to the same tree
    dec_auto = decode_tree(ref_comp, b, tree)
    dec_pl = decode_tree(pl_comp, b, tree)
    for xa, xb in zip(jax.tree.leaves(dec_auto), jax.tree.leaves(dec_pl)):
        np.testing.assert_array_equal(np.asarray(xa), np.asarray(xb))


@pytest.mark.parametrize("impl", ["auto", "xla", "pallas"])
def test_every_impl_same_payload(impl):
    """The three selectable impls agree bit-for-bit on one jitted encode
    (int8+topk, stochastic — the full pipeline)."""
    comp = CompressionConfig(kind="int8+topk", chunk=16, topk_frac=0.25,
                             stochastic=True, kernel_impl=impl)
    ref = CompressionConfig(kind="int8+topk", chunk=16, topk_frac=0.25,
                            stochastic=True)  # default auto
    tree, key = _tree(3), jax.random.key(5)
    a = _jit_encode(encode_tree, ref)(tree, key)
    b = _jit_encode(encode_tree, comp)(tree, key)
    for xa, xb in zip(jax.tree.leaves(a), jax.tree.leaves(b)):
        np.testing.assert_array_equal(np.asarray(xa), np.asarray(xb))


def test_topk_vmem_decline_degrades_to_reference():
    """A top-k row wider than the single-block VMEM budget makes the
    Pallas kernel raise NotImplementedError BEFORE launch; the codec's
    _run_op falls back to the XLA reference, bit-identically — the decline
    is invisible on the wire."""
    n = pallas_codec.TOPK_VMEM_BUDGET_BYTES  # any N past budget/(4*6*br)
    x = jax.random.normal(jax.random.key(0), (8, 60_000), jnp.float32)
    assert 8 * 60_000 * 4 * pallas_codec._TOPK_LIVE_BUFFERS > n
    with pytest.raises(NotImplementedError, match="VMEM"):
        pallas_codec._topk_select_pallas(x, k=5)
    from bcfl_tpu.compression.codecs import _run_op
    va, ia = jax.jit(lambda y: _run_op("topk_select", "xla", y, k=5))(x)
    vb, ib = jax.jit(lambda y: _run_op("topk_select", "pallas", y, k=5))(x)
    np.testing.assert_array_equal(np.asarray(va), np.asarray(vb))
    np.testing.assert_array_equal(np.asarray(ia), np.asarray(ib))


# ----------------------------------------------------------------- harness


def test_registry_rejects_undeclared_op():
    """Unknown op names reject loudly (the "reject nothing" rule is about
    impl degradation, never about typo'd ops); unknown impls too."""
    with pytest.raises(KeyError, match="unknown kernel op"):
        registry.resolve("definitely_not_registered")
    with pytest.raises(KeyError, match="int8_quantize"):
        # the error names the registered ops, so the typo is debuggable
        registry.get_op("int8_quantize_v2")
    with pytest.raises(ValueError, match="impl"):
        registry.resolve("int8_quantize", "cuda")
    with pytest.raises(ValueError, match="kernel_impl"):
        CompressionConfig(kind="int8", kernel_impl="cuda")


def test_registry_degrades_pallas_to_xla_for_xla_only_ops():
    """Explicit kernel_impl="pallas" on an op with no Pallas impl serves
    the XLA reference (decode-side ops are registered XLA-only)."""
    fn, resolved = registry.resolve("int8_dequant", "pallas")
    assert resolved == "xla"
    assert fn is registry.get_op("int8_dequant").xla
    # auto off-TPU is XLA even when a Pallas impl exists
    _, resolved = registry.resolve("int8_quantize", "auto")
    assert resolved == ("pallas" if jax.default_backend() == "tpu"
                        else "xla")


def test_interpret_knob_and_deprecated_alias(monkeypatch):
    monkeypatch.delenv(registry.INTERPRET_ENV, raising=False)
    monkeypatch.delenv(registry.INTERPRET_ENV_DEPRECATED, raising=False)
    # auto: interpret everywhere but on a real TPU backend
    assert registry.interpret_mode() == (jax.default_backend() != "tpu")
    monkeypatch.setenv(registry.INTERPRET_ENV, "0")
    assert registry.interpret_mode() is False
    monkeypatch.setenv(registry.INTERPRET_ENV, "1")
    assert registry.interpret_mode() is True
    monkeypatch.delenv(registry.INTERPRET_ENV)
    monkeypatch.setenv(registry.INTERPRET_ENV_DEPRECATED, "1")
    with pytest.warns(DeprecationWarning, match="BCFL_PALLAS_INTERPRET"):
        assert registry.interpret_mode() is True


def test_legal_block_sizes():
    """The shared Mosaic legalization: a block divides into the dim on the
    tile unit, or IS the dim (then any size is legal)."""
    assert registry.legal_block(256, 1024, 128) == 256
    assert registry.legal_block(2048, 1024, 128) == 1024  # clamp to dim
    assert registry.legal_block(200, 1024, 128) == 128    # floor to unit
    assert registry.legal_block(37, 37, 128) == 37        # == dim: legal
    assert registry.legal_block(64, 100, 128) == 100      # sub-unit dim
    assert registry.legal_block_sizes(
        ((512, 128, 8), (512, 384, 128))) == (128, 384)


# ------------------------------------------------------------ engine seam


def _tiny(**kw):
    base = dict(
        dataset="synthetic", model="tiny-bert", num_clients=4, num_rounds=2,
        seq_len=16, batch_size=4, max_local_batches=2, vocab_size=512,
        partition=PartitionConfig(kind="iid", iid_samples=8),
    )
    base.update(kw)
    return FedConfig(**base)


def test_kernel_impl_excluded_from_wire_format_and_resume(tmp_path):
    """kernel_impl is NOT codec identity: every impl's payload is byte-
    identical, so (a) wire_format strings are equal across impls and (b) a
    checkpointed run resumes under a DIFFERENT kernel_impl without the
    wire-format refusal — unlike a kind/chunk/topk_frac change."""
    comps = [CompressionConfig(kind="int8+topk", topk_frac=0.1,
                               kernel_impl=i) for i in ("auto", "xla",
                                                        "pallas")]
    assert len({wire_format(c) for c in comps}) == 1
    kw = dict(checkpoint_dir=str(tmp_path), checkpoint_every=1,
              eval_every=0)
    FedEngine(_tiny(num_rounds=1, compression=comps[1], **kw)).run()
    res = FedEngine(_tiny(num_rounds=2, compression=comps[2],
                          **kw)).run(resume=True)
    assert len(res.metrics.rounds) == 1  # resumed past round 0, no refusal
