"""Pallas flash kernels run in interpret mode on the CPU mesh: the exact
kernel bodies (forward online-softmax + hand-written dKV/dQ backward) are
exercised in CI without TPU hardware — forward/gradient parity against the
dense oracle across causal, padded, and uneven-block shapes."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bcfl_tpu.ops.attention import attention_bias_from_mask, dot_product_attention
from bcfl_tpu.ops.flash import flash_attention_xla
from bcfl_tpu.ops.pallas_flash import flash_attention as flash_pl


def _qkv(shape, seed=0):
    rng = np.random.default_rng(seed)
    return tuple(jnp.asarray(rng.normal(size=shape), jnp.float32)
                 for _ in range(3))


def test_pallas_forward_matches_dense():
    B, H, S, D = 2, 3, 128, 16
    q, k, v = _qkv((B, H, S, D))
    out = flash_pl(q, k, v, None, False, 64, 64)
    ref = dot_product_attention(q, k, v, None)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_pallas_forward_key_bias_padding():
    B, H, S, D = 2, 2, 128, 8
    q, k, v = _qkv((B, H, S, D), seed=1)
    mask = np.ones((B, S), np.int32)
    mask[0, 100:] = 0
    mask[1, 50:] = 0
    bias4 = attention_bias_from_mask(jnp.asarray(mask))  # [B,1,1,S]
    out = flash_pl(q, k, v, bias4, False, 32, 32)
    ref = dot_product_attention(q, k, v, bias4)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_pallas_forward_causal_uneven_blocks():
    # S=96 does not tile into 64-blocks: exercises tail-block masking
    B, H, S, D = 1, 2, 96, 8
    q, k, v = _qkv((B, H, S, D), seed=2)
    out = flash_pl(q, k, v, None, True, 64, 64)
    ref = flash_attention_xla(q, k, v, None, block_size=96, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)


def test_pallas_backward_matches_dense():
    B, H, S, D = 1, 2, 128, 8
    q, k, v = _qkv((B, H, S, D), seed=3)

    gp = jax.grad(lambda q, k, v: flash_pl(q, k, v, None, False, 32, 32).sum(),
                  (0, 1, 2))(q, k, v)
    gd = jax.grad(lambda q, k, v: dot_product_attention(q, k, v, None).sum(),
                  (0, 1, 2))(q, k, v)
    for a, b in zip(gp, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-5)


def test_pallas_backward_causal_and_padded():
    B, H, S, D = 2, 2, 96, 8  # uneven blocks + padding + causal together
    q, k, v = _qkv((B, H, S, D), seed=4)
    mask = np.ones((B, S), np.int32)
    mask[1, 70:] = 0
    key_bias = jnp.asarray((1 - mask) * -1e30, jnp.float32)

    def f_pl(q, k, v):
        return (flash_pl(q, k, v, key_bias, True, 32, 32)
                * jnp.asarray(mask)[:, None, :, None]).sum()

    def f_ref(q, k, v):
        return (flash_attention_xla(q, k, v, key_bias[:, None, None, :],
                                    block_size=32, causal=True)
                * jnp.asarray(mask)[:, None, :, None]).sum()

    gp = jax.grad(f_pl, (0, 1, 2))(q, k, v)
    gd = jax.grad(f_ref, (0, 1, 2))(q, k, v)
    for a, b in zip(gp, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-5)


def test_pallas_bias_gradient():
    """The hand-written backward produces the key-bias gradient too (the XLA
    oracle differentiates through its dense-bias path)."""
    B, H, S, D = 1, 2, 64, 8
    q, k, v = _qkv((B, H, S, D), seed=5)
    bias = jnp.asarray(np.random.default_rng(6).normal(size=(B, S)) * 0.1,
                       jnp.float32)

    gp = jax.grad(lambda b: flash_pl(q, k, v, b, False, 32, 32).sum())(bias)
    gd = jax.grad(lambda b: flash_attention_xla(
        q, k, v, b[:, None, None, :], block_size=32).sum())(bias)
    np.testing.assert_allclose(np.asarray(gp), np.asarray(gd), atol=3e-5)


def test_pallas_suffix_causal_alignment():
    # Sq != Sk (decode pattern): query at local 0 = global position Sk - Sq
    B, H, S, D = 1, 2, 64, 8
    q, k, v = _qkv((B, H, S, D), seed=7)
    full = flash_pl(q, k, v, None, True, 16, 16)
    tail = flash_pl(q[:, :, -16:], k, v, None, True, 16, 16)
    np.testing.assert_allclose(np.asarray(tail), np.asarray(full[:, :, -16:]),
                               atol=2e-5)


def test_pallas_bf16_under_jit():
    B, H, S, D = 1, 2, 256, 8
    q = jnp.ones((B, H, S, D), jnp.bfloat16)
    out = jax.jit(lambda a: flash_pl(a, a, a, None, False, 128, 128))(q)
    assert out.shape == (B, H, S, D) and out.dtype == jnp.bfloat16
    assert np.isfinite(np.asarray(out, np.float32)).all()


def test_pallas_backward_uneven_blocks():
    """Backward parity when S does not tile into blocks: the padded-tail
    branch (_zero_oob_rows + qrow>=sq dead-masking) feeds the dk/dv/db
    accumulators — a regression there corrupts gradients silently."""
    B, H, S, D = 2, 2, 80, 8  # 80 / 32 -> tail block of 16 rows
    q, k, v = _qkv((B, H, S, D), seed=8)
    bias = jnp.asarray(np.random.default_rng(9).normal(size=(B, S)) * 0.1,
                       jnp.float32)

    gp = jax.grad(lambda q, k, v, b: flash_pl(q, k, v, b, True, 32, 32).sum(),
                  (0, 1, 2, 3))(q, k, v, bias)
    gd = jax.grad(lambda q, k, v, b: flash_attention_xla(
        q, k, v, b[:, None, None, :], block_size=S, causal=True).sum(),
        (0, 1, 2, 3))(q, k, v, bias)
    for a, b in zip(gp, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=3e-5)


def test_pallas_odd_block_sizes_clamped():
    """Real-TPU Mosaic requires block last-two dims to divide (8, 128) or
    equal the array dims; odd caller block sizes are clamped to the nearest
    legal ones (caught on silicon — a (1, bk) bias block failed lowering at
    every seq length while interpret mode passed)."""
    from bcfl_tpu.ops.pallas_flash import _block_sizes

    assert _block_sizes(200, 200, 512, 512) == (200, 128)  # bq 200 % 8 == 0
    assert _block_sizes(67, 130, 512, 512) == (64, 128)
    assert _block_sizes(256, 256, 96, 96) == (96, 96)  # == dims: legal as-is
    assert _block_sizes(4, 64, 512, 512) == (8, 128)  # floors at one tile
    # sub-tile request on a sub-tile-multiple dim: the whole dim is the
    # nearest legal block (bk=128 > Sk=96 would pad 32 dead lanes)
    assert _block_sizes(64, 64, 96, 96) == (64, 96)
    assert _block_sizes(4, 64, 6, 6) == (6, 6)  # dim smaller than a tile

    B, H, S, D = 2, 2, 96, 16
    q, k, v = _qkv((B, H, S, D))
    out = flash_pl(q, k, v, None, False, 67, 130)  # odd blocks, clamped
    ref = dot_product_attention(q, k, v, None)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-5)
