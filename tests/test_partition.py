import jax
import numpy as np

from bcfl_tpu.config import PartitionConfig
from bcfl_tpu.data.partition import Partitioner, contiguous_indices, iid_indices


def test_iid_deterministic_and_disjoint_keys():
    key = jax.random.key(0)
    a = iid_indices(key, 1000, 100)
    b = iid_indices(key, 1000, 100)
    np.testing.assert_array_equal(a, b)
    assert len(set(a.tolist())) == 100  # without replacement
    c = iid_indices(jax.random.fold_in(key, 1), 1000, 100)
    assert not np.array_equal(a, c)


def test_contiguous_imdb_schedule():
    # the 300k/240 IMDB schedule (serverless_NonIID_IMDB.py:59-60)
    for k in range(5):
        train, test = contiguous_indices(k, 300, 240, 60, 25000, 25000, "trailing")
        assert train[0] == 300 * k and train[-1] == 300 * k + 239
        assert test[0] == 300 * k + 240 and test[-1] == 300 * (k + 1) - 1


def test_contiguous_medical_schedule_fixed_test():
    # the 500i/400 medical schedule (Serverless_NonIID_Medical_transcriptions.py:55-56)
    for i in range(3):
        train, test = contiguous_indices(i, 500, 400, 400, 12021, 3003, "fixed")
        assert train[0] == 500 * i and train.size == 400
        np.testing.assert_array_equal(test, np.arange(400))


def test_contiguous_clips_and_wraps():
    train, test = contiguous_indices(100, 300, 240, 60, 1000, 1000, "trailing")
    assert train.size > 0 and train.max() < 1000
    assert test.size == 0 or test.max() < 1000


def test_partitioner_resample_each_round():
    cfg = PartitionConfig(kind="iid", iid_samples=50, resample_each_round=True)
    p = Partitioner(cfg, 1000, 1000, jax.random.key(7))
    t0, _ = p.train_test_indices(0, 0)
    t1, _ = p.train_test_indices(0, 1)
    assert not np.array_equal(t0, t1)

    cfg2 = PartitionConfig(kind="iid", iid_samples=50, resample_each_round=False)
    p2 = Partitioner(cfg2, 1000, 1000, jax.random.key(7))
    s0, _ = p2.train_test_indices(0, 0)
    s1, _ = p2.train_test_indices(0, 1)
    np.testing.assert_array_equal(s0, s1)
