"""Regression: the engine's round programs must compile exactly once.

The r04 bench recorded 87.5 s/dispatch because the warmup call's input
params were single-device committed while its output carried the program's
``out_shardings`` — so the SECOND call was a new jit cache entry (a full
recompile) that landed inside the timed loop (PERF.md, results/
dispatch_bisect.json). ``FedEngine.__init__`` now pins ``trainable0`` /
``frozen`` to their steady-state shardings; this test pins THAT by counting
jit cache entries after a multi-round run. A second cache entry on any round
program is this bug come back (on a tunnelled TPU it costs minutes per
round-2 dispatch).
"""

import pytest

from bcfl_tpu.config import FedConfig, PartitionConfig
from bcfl_tpu.fed.engine import FedEngine

pytestmark = pytest.mark.slow  # engine-suite tier: compile-heavy on the
# 8-device CPU mesh; the tier-1 'not slow' window runs the chaos matrix
# (tests/test_faults.py) as its fast engine coverage instead


@pytest.fixture(autouse=True)
def _fresh_programs(monkeypatch):
    """These tests count jit cache entries PER ENGINE; the cross-engine
    program cache deliberately accumulates one entry per tree structure on
    shared objects (e.g. lora adapters after full params), which is correct
    behavior but not what this regression pins. Disable sharing here."""
    monkeypatch.setenv("BCFL_PROGRAM_CACHE", "0")


def _run(mode, **kw):
    cfg = FedConfig(
        name=f"recompile_{mode}", model="tiny-bert", dataset="synthetic",
        mode=mode, num_clients=4, num_rounds=3, seq_len=16, batch_size=4,
        max_local_batches=2,
        partition=PartitionConfig(kind="iid", iid_samples=8,
                                  resample_each_round=True),
        **kw,
    )
    eng = FedEngine(cfg)
    res = eng.run()
    assert len(res.metrics.rounds) == 3
    return eng


@pytest.mark.parametrize("mode", ["server", "serverless"])
def test_round_programs_compile_once(mode):
    eng = _run(mode)
    progs = eng.progs
    # the mode's primary round program MUST have compiled exactly once —
    # == 1, not <= 1, so the test cannot pass vacuously if a future engine
    # routes rounds elsewhere (then update this map: it pins the hot path)
    hot = "server_round" if mode == "server" else "gossip_round"
    assert getattr(progs, hot)._cache_size() == 1, hot
    for name in ("server_round", "server_rounds", "server_rounds_static",
                 "gossip_round", "gossip_rounds", "gossip_rounds_static",
                 "eval_clients", "eval_clients_global", "eval_global",
                 "client_updates", "local_updates", "mix_only", "collapse"):
        size = getattr(progs, name)._cache_size()
        # uncalled programs are 0; any program the run used must be 1
        assert size <= 1, f"{name} compiled {size}x across a 3-round run"


def test_lora_round_programs_compile_once():
    eng = _run("server", lora_rank=2)
    size = eng.progs.server_round._cache_size()
    assert size == 1, f"lora server_round compiled {size}x (0 = not the hot path)"


def test_async_round_programs_compile_once():
    """The buffered-async path chains stacked params through
    local_updates -> collapse -> broadcast -> select every round; any
    sharding drift in that chain recompiles local_updates round over round."""
    eng = _run("serverless", sync="async", async_buffer=2)
    assert eng.progs.local_updates._cache_size() == 1
    assert eng.progs.collapse._cache_size() <= 1
