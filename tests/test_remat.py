"""Per-layer rematerialization: identical math, less activation memory.

remat must be a pure memory/FLOPs trade — forward logits and gradients
bit-match the non-remat model on the same params, for both families, and
the knob must flow FedConfig -> engine -> model."""

import jax
import jax.numpy as jnp
import pytest

from bcfl_tpu.models import build


@pytest.mark.parametrize("name,kw,grad_tol", [
    ("tiny-bert", {}, 0.0),
    ("tiny-albert", {}, 0.0),  # share_layers path wraps the shared layer once
    ("tiny-llama", {}, 1e-6),
])
def test_remat_is_numerically_identical(name, kw, grad_tol):
    """Forward logits must be BIT-identical for every family (remat replays
    the same forward graph). Gradients are bit-identical for the encoders,
    but tiny-llama's differ from the non-remat build by ~7e-8 max-abs
    (float32): remat recomputes the RMSNorm/SiLU forward INSIDE the backward
    pass, and XLA fuses that recomputation with the surrounding backward ops
    differently from the stored-activation graph — the rsqrt/mean
    contractions re-associate by ~1 ulp. Same math, different float
    summation order; ``grad_tol=1e-6`` absolute bounds it (observed 6.6e-8)
    so a real remat semantics bug (wrong policy, dropped term — errors of
    1e-2-scale) still fails loudly."""
    m0 = build(name, num_labels=2, **kw)
    m1 = build(name, num_labels=2, remat=True, **kw)
    ids = jnp.ones((2, 16), jnp.int32)
    params = m0.init(jax.random.key(0), ids, ids)["params"]

    def loss(m):
        return lambda p: m.apply({"params": p}, ids, ids).astype(
            jnp.float32).sum()

    assert float(jnp.abs(m0.apply({"params": params}, ids, ids)
                         - m1.apply({"params": params}, ids, ids)).max()) == 0
    g0 = jax.grad(loss(m0))(params)
    g1 = jax.grad(loss(m1))(params)
    assert max(jax.tree.leaves(jax.tree.map(
        lambda a, b: float(jnp.abs(a - b).max()), g0, g1))) <= grad_tol


@pytest.mark.slow  # full engine/CLI run: deeper-tier budget
def test_remat_engine_round():
    from bcfl_tpu.config import FedConfig, PartitionConfig
    from bcfl_tpu.fed.engine import FedEngine

    eng = FedEngine(FedConfig(
        name="remat", model="tiny-bert", dataset="synthetic",
        num_clients=2, num_rounds=1, seq_len=16, batch_size=4,
        max_local_batches=1, remat=True,
        partition=PartitionConfig(kind="iid", iid_samples=8)))
    assert eng.model.cfg.remat is True
    res = eng.run()
    assert jnp.isfinite(res.metrics.rounds[0].train_loss)
