"""Peer-lifecycle chaos matrix: reputation-driven quarantine
(bcfl_tpu.reputation) and the partition / churn / flaky fault lanes
(bcfl_tpu.faults) against the engine's ROBUSTNESS.md §6 contracts.

Pinned here:

- a partitioned span degrades to per-component aggregation and reconciles
  deterministically on heal (no NaN, no silent global average of divergent
  components),
- a flaky repeat offender is quarantined within the configured window,
  excluded from aggregation while quarantined, and readmitted on probation
  at reduced weight — while a single-round glitch is never quarantined,
- churn (permanent leave / late join) is a pure mask schedule: the mesh
  never reshapes, absent clients carry weight 0,
- crash + restore + re-run under partition + churn + flaky reproduces the
  uninterrupted run BIT-FOR-BIT, with reputation state restored from the
  checkpoint, composing with aggregator=trimmed_mean, compress=int8+topk,
  and the ledger — at zero per-round retraces.

Rides the tier-1 chaos matrix (marker ``faults``, plus the focused
``reputation`` marker — ``scripts/chaos_smoke.sh`` runs both).
"""

import dataclasses

import numpy as np
import pytest

import jax

from bcfl_tpu.config import FedConfig, LedgerConfig, PartitionConfig
from bcfl_tpu.faults import FaultInjector, FaultPlan, SimulatedCrash
from bcfl_tpu.fed.engine import FedEngine
from bcfl_tpu.reputation import (
    HEALTHY,
    PROBATION,
    QUARANTINED,
    SUSPECT,
    ReputationConfig,
    ReputationTracker,
)

pytestmark = [pytest.mark.faults, pytest.mark.reputation]


def _tiny(**kw):
    """Same smallest-config shape as tests/test_faults.py so the memoized
    round programs (and the persistent XLA cache) are shared across the
    chaos matrix."""
    base = dict(
        dataset="synthetic", model="tiny-bert", num_clients=4, num_rounds=3,
        seq_len=16, batch_size=4, max_local_batches=2,
        partition=PartitionConfig(kind="iid", iid_samples=8),
    )
    base.update(kw)
    return FedConfig(**base)


def _leaves(tree):
    return jax.tree.leaves(jax.device_get(tree))


def _assert_finite(tree):
    for x in _leaves(tree):
        assert np.isfinite(np.asarray(x)).all(), "NaN/Inf in global model"


def _assert_trees_equal(a, b):
    for x, y in zip(_leaves(a), _leaves(b)):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


# ----------------------------------------------------------------- plan lanes


def test_partition_lane_deterministic_and_validated():
    plan = FaultPlan(partition_groups=((0, 1), (2, 3)),
                     partition_rounds=(1, 2))
    assert plan.partitions and plan.enabled
    assert plan.partition_components(0, 4) is None
    assert plan.partition_components(1, 4) == ((0, 1), (2, 3))
    # unlisted clients form their own component, never vanish
    assert plan.partition_components(1, 6) == ((0, 1), (2, 3), (4, 5))
    # seeded split: stable across the span, every component non-empty,
    # different seeds give different splits
    p = FaultPlan(seed=3, partition_count=2, partition_rounds=(0, 1, 2))
    comps = p.partition_components(0, 8)
    assert comps == p.partition_components(2, 8)
    assert sorted(c for g in comps for c in g) == list(range(8))
    assert len(comps) == 2 and all(g for g in comps)
    q = FaultPlan(seed=4, partition_count=2, partition_rounds=(0, 1, 2))
    assert any(q.partition_components(0, 8) != p.partition_components(0, 8)
               for _ in range(1))
    # a single explicit group is fine: unlisted clients form the other side
    half = FaultPlan(partition_groups=((0, 1),), partition_rounds=(0,))
    assert half.partition_components(0, 4) == ((0, 1), (2, 3))
    # validation
    with pytest.raises(ValueError, match="disjoint"):
        FaultPlan(partition_groups=((0, 1), (1, 2)), partition_rounds=(0,))
    with pytest.raises(ValueError, match="partition_rounds"):
        FaultPlan(partition_groups=((0,), (1,)))
    with pytest.raises(ValueError, match="empty"):
        # a typo'd START:END span collapsing to () must not pass vacuously
        FaultPlan(partition_groups=((0,), (1,)), partition_rounds=())
    with pytest.raises(ValueError, match="not both"):
        FaultPlan(partition_groups=((0,), (1,)), partition_count=2,
                  partition_rounds=(0,))
    with pytest.raises(ValueError, match="only 4 clients"):
        FaultInjector(FaultPlan(partition_groups=((0,), (9,)),
                                partition_rounds=(0,)), 4)
    with pytest.raises(ValueError, match="effective components"):
        # one group covering every client splits nothing
        FaultInjector(FaultPlan(partition_groups=((0, 1, 2, 3),),
                                partition_rounds=(0,)), 4)


def test_churn_schedule_is_monotone_mask():
    plan = FaultPlan(churn_leave=((3, 2),), churn_join=((0, 1),))
    assert plan.churns and plan.enabled
    rows = [plan.churn_alive(r, 4).tolist() for r in range(4)]
    assert rows[0] == [0.0, 1.0, 1.0, 1.0]   # 0 not yet joined
    assert rows[1] == [1.0, 1.0, 1.0, 1.0]   # 0 joined, 3 still here
    assert rows[2] == [1.0, 1.0, 1.0, 0.0]   # 3 left permanently
    assert rows[3] == [1.0, 1.0, 1.0, 0.0]
    assert FaultPlan().churn_alive(0, 4) is None
    with pytest.raises(ValueError, match="permanent"):
        FaultPlan(churn_leave=((1, 2),), churn_join=((1, 3),))
    with pytest.raises(ValueError, match="twice"):
        FaultPlan(churn_leave=((1, 2), (1, 3)))


def test_flaky_bursts_are_multi_round_and_seeded():
    plan = FaultPlan(seed=7, flaky_clients=(1,), flaky_burst_len=3,
                     flaky_on_prob=0.5, flaky_scale=42.0)
    assert plan.flaky_enabled and plan.corrupts and plan.enabled
    rows = [plan.flaky_scales(r, 4) for r in range(12)]
    # deterministic: a second draw reproduces the schedule exactly
    for r, row in enumerate(rows):
        again = plan.flaky_scales(r, 4)
        if row is None:
            assert again is None
        else:
            np.testing.assert_array_equal(row, again)
    # burst windows are whole: within a 3-round window the client is either
    # bad for all 3 rounds or clean for all 3
    for w in range(4):
        vals = {tuple(r.tolist()) if r is not None else None
                for r in rows[3 * w:3 * w + 3]}
        assert len(vals) == 1, f"window {w} not constant: {vals}"
    # at p=0.5 over 4 windows the seeded schedule has both bursts and gaps
    assert any(r is not None for r in rows), "flaky lane never fired"
    assert any(r is None for r in rows), "flaky lane always on at p=0.5"
    # only the flaky client is ever corrupted
    for row in rows:
        if row is not None:
            assert row[1] == 42.0 and row[0] == row[2] == row[3] == 0.0
    # the injector merges flaky into the one transport_scales call site
    inj = FaultInjector(plan, 4)
    burst = next(r for r in range(12)
                 if plan.flaky_scales(r, 4) is not None)
    np.testing.assert_array_equal(inj.transport_scales(burst),
                                  plan.flaky_scales(burst, 4))


# ------------------------------------------------------------- state machine


def test_lifecycle_repeat_offender_vs_single_glitch():
    cfg = ReputationConfig(enabled=True, quarantine_rounds=2,
                           probation_rounds=2)
    t = ReputationTracker(cfg, 2)
    # client 1 offends twice -> SUSPECT then QUARANTINED; client 0 clean
    t.observe(np.asarray([0.0, 1.0]))
    assert t.state.tolist() == [HEALTHY, SUSPECT]
    t.observe(np.asarray([0.0, 1.0]))
    assert t.state.tolist() == [HEALTHY, QUARANTINED]
    assert t.gate().tolist() == [1.0, 0.0]
    # sentence ticks evidence-free, then probation at reduced weight
    t.observe(np.zeros(2))
    t.observe(np.zeros(2))
    assert t.state.tolist() == [HEALTHY, PROBATION]
    assert t.gate().tolist() == [1.0, cfg.probation_weight]
    # a strike on probation goes straight back to quarantine
    t.observe(np.asarray([0.0, 1.0]))
    assert t.state.tolist() == [HEALTHY, QUARANTINED]
    assert t.quarantine_events.tolist() == [0, 2]
    # single glitch on a fresh tracker: suspect, then recovery — never
    # quarantined
    t2 = ReputationTracker(cfg, 1)
    t2.observe(np.asarray([1.0]))
    assert t2.state.tolist() == [SUSPECT]
    for _ in range(3):
        t2.observe(np.zeros(1))
    assert t2.state.tolist() == [HEALTHY]
    assert t2.quarantine_events.tolist() == [0]
    # checkpoint round-trip is exact
    t3 = ReputationTracker(cfg, 2)
    t3.restore(t.checkpoint_state())
    np.testing.assert_array_equal(t3.trust, t.trust)
    np.testing.assert_array_equal(t3.state, t.state)
    np.testing.assert_array_equal(t3.timer, t.timer)


def test_reputation_config_validation():
    with pytest.raises(ValueError, match="ewma_alpha"):
        ReputationConfig(ewma_alpha=0.0)
    with pytest.raises(ValueError, match="thresholds"):
        ReputationConfig(suspect_below=0.3, quarantine_below=0.5)
    with pytest.raises(ValueError, match="probation_weight"):
        ReputationConfig(probation_weight=0.0)


# ------------------------------------------------------- engine: quarantine


def test_flaky_repeat_offender_quarantined_glitch_is_not():
    """The headline contract: with the ledger producing the evidence, a
    client that fails auth two rounds running is quarantined (mask 0 for
    the window), readmitted on probation at reduced weight, and healthy
    after serving it; a single-round glitch only ever reaches SUSPECT."""
    rep = ReputationConfig(enabled=True, quarantine_rounds=2,
                           probation_rounds=2, probation_weight=0.5)
    offender = _tiny(
        mode="server", num_rounds=7, eval_every=0,
        ledger=LedgerConfig(enabled=True), reputation=rep,
        faults=FaultPlan(corrupt_prob=1.0, corrupt_rounds=(0, 1),
                         corrupt_scale=1e6))
    eng = FedEngine(offender)
    assert eng._chunk_rounds(0) == 1  # reputation forces the per-round path
    res = eng.run()
    recs = res.metrics.rounds
    # rounds 0-1: every client fails auth (corrupt_prob=1) -> trust
    # 1.0 -> 0.6 -> 0.36: quarantined from round 2, within the window
    assert recs[0].reputation_state == ["suspect"] * 4
    assert recs[1].reputation_state == ["quarantined"] * 4
    for r in (2, 3):
        assert recs[r].mask == [0.0] * 4          # excluded while inside
        assert recs[r].degraded is True           # nobody left to aggregate
    assert recs[3].reputation_state == ["probation"] * 4
    for r in (4, 5):
        assert recs[r].mask == [0.5] * 4          # probation vote weight
    assert recs[5].reputation_state == ["healthy"] * 4
    assert recs[6].mask == [1.0] * 4
    _assert_finite(res.trainable)
    roll = res.metrics.reputation
    assert roll["total_quarantine_events"] == 4
    assert roll["rounds_quarantined"] == [2] * 4

    # contrast: ONE bad round is a glitch — suspect, recover, never
    # quarantined, never excluded
    glitch = FedEngine(offender.replace(
        faults=FaultPlan(corrupt_prob=1.0, corrupt_rounds=(0,),
                         corrupt_scale=1e6))).run()
    assert glitch.metrics.rounds[0].reputation_state == ["suspect"] * 4
    assert glitch.metrics.reputation["total_quarantine_events"] == 0
    for r in glitch.metrics.rounds:
        assert all(m > 0.0 for m in r.mask), "glitch must never exclude"


# -------------------------------------------------------- engine: partition


def test_partitioned_round_aggregates_per_component():
    """During a partitioned round each component converges to ITS OWN
    aggregate: rows agree within a component, differ across components, and
    nothing NaNs. The consensus view is the robust cross-component
    reconciliation, not a fresh global average of raw client updates."""
    eng = FedEngine(_tiny(mode="server", num_rounds=1, faults=FaultPlan(
        partition_groups=((0, 1), (2, 3)), partition_rounds=(0,))))
    comps = eng.faults.partition_components(0)
    consensus, out, rec = eng._partitioned_round(
        0, eng.trainable0, None, np.ones(4, np.float32), comps)
    assert rec.partition == [0, 0, 1, 1]
    _assert_finite(out)
    _assert_finite(consensus)
    host = jax.device_get(out)
    leaf = np.asarray(jax.tree.leaves(host)[0])
    np.testing.assert_array_equal(leaf[0], leaf[1])  # same component
    np.testing.assert_array_equal(leaf[2], leaf[3])
    assert not np.array_equal(leaf[0], leaf[2]), (
        "components silently shared an aggregate across the partition")


def test_partition_span_heals_deterministically():
    """A full run through a partition span: partitioned rounds record
    component ids, the first whole round records healed=True, the final
    model is finite, and two identical runs are bit-identical (the
    reconciliation is deterministic)."""
    cfg = _tiny(mode="server", num_rounds=4, eval_every=0,
                faults=FaultPlan(partition_groups=((0, 1), (2, 3)),
                                 partition_rounds=(1, 2)))
    res_a = FedEngine(cfg).run()
    recs = res_a.metrics.rounds
    assert recs[0].partition is None and recs[3].partition is None
    assert recs[1].partition == [0, 0, 1, 1]
    assert recs[2].partition == [0, 0, 1, 1]
    assert [r.healed for r in recs] == [False, False, False, True]
    _assert_finite(res_a.trainable)
    res_b = FedEngine(cfg).run()
    _assert_trees_equal(res_a.trainable, res_b.trainable)
    # the partition changed the outcome vs the unpartitioned run (the spans
    # really did aggregate independently)
    res_c = FedEngine(_tiny(mode="server", num_rounds=4, eval_every=0)).run()
    assert any(
        not np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(_leaves(res_a.trainable), _leaves(res_c.trainable)))


def test_partitioned_info_passing_restricted_to_component():
    """Information from the source crosses only its own component during a
    partition: sync time shrinks to the in-component targets."""
    cfg = _tiny(mode="serverless", num_rounds=2,
                topology=dataclasses.replace(_tiny().topology,
                                             gossip_steps=0),
                faults=FaultPlan(partition_groups=((0, 1), (2, 3)),
                                 partition_rounds=(0,)))
    res = FedEngine(cfg).run()
    r0, r1 = res.metrics.rounds
    assert r0.info_passing_sync_s < r1.info_passing_sync_s
    assert r1.healed is True


def test_partition_rejected_where_it_cannot_aggregate():
    plan = FaultPlan(partition_groups=((0, 1), (2, 3)),
                     partition_rounds=(0,))
    with pytest.raises(ValueError, match="async"):
        _tiny(sync="async", faults=plan)
    with pytest.raises(ValueError, match="faithful"):
        _tiny(mode="serverless", faithful=True, faults=plan)
    with pytest.raises(ValueError, match="gossip_steps"):
        _tiny(mode="serverless", faults=plan)  # default ring diffusion


# ------------------------------------------------------------ engine: churn


def test_churn_leave_and_late_join_are_mask_schedules():
    cfg = _tiny(mode="server", num_rounds=3, eval_every=0,
                faults=FaultPlan(churn_leave=((3, 1),),
                                 churn_join=((0, 1),)))
    res = FedEngine(cfg).run()
    recs = res.metrics.rounds
    assert recs[0].churn_alive == [0.0, 1.0, 1.0, 1.0]
    assert recs[0].mask[0] == 0.0            # not yet joined
    assert recs[1].churn_alive == [1.0, 1.0, 1.0, 0.0]
    assert recs[1].mask == [1.0, 1.0, 1.0, 0.0]
    assert recs[2].mask[3] == 0.0            # leave is permanent
    _assert_finite(res.trainable)


# ------------------------------------- composition: the §6 chaos-matrix case


def test_partition_churn_flaky_crash_resume_bit_identical(tmp_path):
    """The composition contract in one chaos-matrix case: partition + churn
    + flaky with aggregator=trimmed_mean, compress=int8+topk, and the
    ledger on — zero per-round retraces, and crash + restore + re-run
    reproduces the uninterrupted run bit-for-bit with reputation state
    carried in the checkpoint.

    The trimmed_mean x int8+topk program set is unique to this test, so the
    jit cache sizes below count exactly this test's traces — asserted ==1
    AFTER three engine runs (uninterrupted, crashed, resumed), which pins
    both zero per-ROUND retraces and zero per-ENGINE recompiles (masks,
    weights, components, and reputation gates are all runtime inputs)."""
    from bcfl_tpu.compression import CompressionConfig

    base = _tiny(
        mode="server", num_rounds=5, eval_every=0,
        aggregator="trimmed_mean",
        compression=CompressionConfig(kind="int8+topk"),
        ledger=LedgerConfig(enabled=True),
        reputation=ReputationConfig(enabled=True, quarantine_rounds=2),
        faults=FaultPlan(
            seed=11,
            partition_groups=((0, 1), (2, 3)), partition_rounds=(1, 2),
            churn_leave=((2, 4),), churn_join=((3, 1),),
            flaky_clients=(1,), flaky_burst_len=2, flaky_on_prob=1.0),
        checkpoint_dir=str(tmp_path / "a"), checkpoint_every=1)
    eng_a = FedEngine(base)
    res_a = eng_a.run()
    # the lanes actually fired
    assert any(r.partition for r in res_a.metrics.rounds)
    assert any(r.auth and 0.0 in r.auth for r in res_a.metrics.rounds)
    assert res_a.metrics.reputation["total_quarantine_events"] >= 1
    _assert_finite(res_a.trainable)

    crash = base.replace(
        checkpoint_dir=str(tmp_path / "b"),
        faults=dataclasses.replace(base.faults, crash_at_round=3))
    with pytest.raises(SimulatedCrash):
        FedEngine(crash).run()
    eng_b = FedEngine(crash)
    res_b = eng_b.run(resume=True)
    # zero per-round retraces: every program the chaos round bodies touch
    # traced exactly once across three engines x 5 rounds (partitioned AND
    # whole-mesh, quarantine on AND off). encode_deltas_local shares its
    # underlying jit with encode_deltas (jax dedupes jit() of the same
    # function), so it carries one trace per delta-REFERENCE kind —
    # replicated global (whole-mesh server rounds) + stacked round-start
    # (partitioned rounds) — a constant 2, not a per-round count.
    for eng in (eng_a, eng_b):
        for name in ("local_updates", "client_updates", "collapse", "adopt",
                     "encode_deltas_local", "fingerprint",
                     "corrupt_payload"):
            prog = getattr(eng.progs, name)
            want = 2 if name == "encode_deltas_local" else 1
            assert prog._cache_size() == want, (name, prog._cache_size())
    # resumed mid-lifecycle: rounds 3-4 re-run with the tracker state (and
    # EF residual, ledger, stacked partition view) restored from round 2's
    # checkpoint — outputs bit-equal to the uninterrupted run
    assert [r.round for r in res_b.metrics.rounds] == [3, 4]
    _assert_trees_equal(res_a.trainable, res_b.trainable)
    for ra, rb in zip(res_a.metrics.rounds[3:], res_b.metrics.rounds):
        assert ra.mask == rb.mask
        assert ra.reputation_state == rb.reputation_state
        assert ra.reputation_trust == rb.reputation_trust
        assert ra.auth == rb.auth
    assert (res_a.metrics.reputation["final_trust"]
            == res_b.metrics.reputation["final_trust"])
    # the checkpoint genuinely carries the tracker arrays
    from bcfl_tpu.checkpoint import restore_latest

    _, state, _ = restore_latest(str(tmp_path / "a"))
    for key in ("rep_trust", "rep_state", "rep_timer"):
        assert state.get(key) is not None, key
