"""run_results rendering helpers: the mode-ordering note's per-suffix pair
scan, sibling-exclusion in prefix lookups, and the reference-column match
for --key-suffix rows. Pure host-side (no backend), so these run in
milliseconds — they pin the machinery that writes RESULTS.md's derived
ordering block (reference README.md:10's headline claims)."""

import importlib.util
import os


_SPEC = importlib.util.spec_from_file_location(
    "run_results", os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "run_results.py"))
rr = importlib.util.module_from_spec(_SPEC)
_SPEC.loader.exec_module(rr)


def _entry(model="tiny-bert", rounds=20, final=0.3, wall=20.0, **kw):
    e = {"model": model, "rounds": rounds, "seq_len": 64,
         "hf_weights": False, "clients": 10, "max_eval_batches": 32,
         "eval_every": 1, "final_acc": final, "wall_minutes": wall}
    e.update(kw)
    return e


def test_ordering_note_matches_within_suffix(tmp_path):
    summary = {
        "server_iid_medical": _entry(final=0.32, wall=26.0),
        "serverless_noniid_medical": _entry(final=0.31, wall=21.0),
        "server_iid_medical_smallbert": _entry("small-bert", 8, 0.40, 60.0),
        "serverless_noniid_medical_smallbert": _entry(
            "small-bert", 8, 0.44, 55.0),
    }
    note = rr._mode_ordering_note(summary, str(tmp_path))
    assert note.count("Matched budget") == 2
    assert "tiny-bert, 10 clients, 20 rounds" in note
    assert "small-bert, 10 clients, 8 rounds" in note


def test_ordering_note_skips_mismatched_budgets(tmp_path):
    summary = {
        "server_iid_medical_x": _entry(rounds=20),
        "serverless_noniid_medical_x": _entry(rounds=8),  # budget differs
    }
    assert rr._mode_ordering_note(summary, str(tmp_path)) == ""


def test_ordering_note_requires_both_modes(tmp_path):
    summary = {"server_iid_medical_smallbert": _entry("small-bert")}
    assert rr._mode_ordering_note(summary, str(tmp_path)) == ""


def test_pair_lines_state_signs():
    sv = _entry(final=0.32, wall=26.0)
    sl = _entry(final=0.31, wall=21.0)
    text = "\n".join(rr._pair_ordering_lines(sv, sl))
    # acc gap negative, latency gap negative (serverless faster)
    assert "does NOT reproduce" in text and "REPRODUCES" in text


def test_pair_lines_count_pointwise_leads():
    sv = _entry(final=0.408, acc_curve=[0.18, 0.31, 0.35, 0.408],
                acc_rounds=[2, 4, 6, 8])
    sl = _entry(final=0.402, acc_curve=[0.21, 0.32, 0.35, 0.402],
                acc_rounds=[2, 4, 6, 8])
    text = "\n".join(rr._pair_ordering_lines(sv, sl))
    assert "serverless led at 2 of 4 shared eval points" in text
    # mismatched eval cadences: no point-wise claim
    sl2 = dict(sl, acc_rounds=[1, 2, 3, 4])
    text = "\n".join(rr._pair_ordering_lines(sv, sl2))
    assert "Point-wise" not in text
    # pre-acc_rounds summaries (older rows): equal-length curves still get
    # the line — the caller already matched rounds and eval cadence
    sv3 = {k: v for k, v in sv.items() if k != "acc_rounds"}
    sl3 = {k: v for k, v in sl.items() if k != "acc_rounds"}
    text = "\n".join(rr._pair_ordering_lines(sv3, sl3))
    assert "serverless led at 2 of 4 shared eval points" in text


def test_pair_lines_disclose_reduced_iid_draw():
    sv = _entry(final=0.32, wall=26.0, iid_samples=400)
    sl = _entry(final=0.35, wall=21.0)
    text = "\n".join(rr._pair_ordering_lines(sv, sl))
    assert "400 IID samples/client/round (server leg)" in text
    # absent from the summary (older rows): no disclosure clause
    text = "\n".join(rr._pair_ordering_lines(_entry(), _entry()))
    assert "IID samples" not in text


def test_faithful_line_emitted_at_matched_budget(tmp_path):
    summary = {
        "server_iid_medical_x": _entry("small-bert", 8, 0.408),
        "serverless_noniid_medical_x": _entry("small-bert", 8, 0.402),
        "faithful_noniid_medical_x": _entry("small-bert", 8, 0.47),
    }
    note = rr._mode_ordering_note(summary, str(tmp_path))
    assert "Faithful serverless" in note
    assert "REPRODUCES under its own sequential semantics" in note
    # mismatched budget: the faithful line is withheld
    summary["faithful_noniid_medical_x"]["rounds"] = 20
    note = rr._mode_ordering_note(summary, str(tmp_path))
    assert "Faithful serverless" not in note


def test_worker_pair_lines_read_artifact(tmp_path):
    import json

    wp = {"model": "small-bert", "rounds": 4, "seq_len": 96,
          "iid_samples": 250,
          "runs": {"5": {"final_acc": 0.199}, "20": {"final_acc": 0.215}}}
    with open(tmp_path / "worker_pair_smallbert.json", "w") as f:
        json.dump(wp, f)
    lines = rr._worker_pair_lines(str(tmp_path))
    assert any("5 workers 0.199 -> 20 workers 0.215" in l for l in lines)
    assert any("rises" in l for l in lines)


def test_worker_pair_lines_missing_artifact(tmp_path):
    assert rr._worker_pair_lines(str(tmp_path)) == []
