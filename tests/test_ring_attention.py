"""Ring attention (sequence parallelism): exact vs dense attention on the
8-device CPU mesh, including padding masks and gradients."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from bcfl_tpu.ops.attention import attention_bias_from_mask, dot_product_attention
from bcfl_tpu.parallel.ring_attention import ring_attention_sharded


def _mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("seq",))


def _rand(key, shape):
    return jax.random.normal(key, shape, jnp.float32)


@pytest.mark.parametrize("n_dev", [2, 4, 8])
def test_matches_dense(n_dev):
    B, H, S, D = 2, 4, 64, 16
    ks = jax.random.split(jax.random.key(0), 3)
    q, k, v = (_rand(kk, (B, H, S, D)) for kk in ks)
    dense = dot_product_attention(q, k, v, None)
    ring = ring_attention_sharded(q, k, v, None, _mesh(n_dev))
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense),
                               atol=2e-5, rtol=2e-5)


def test_matches_dense_with_padding_mask():
    B, H, S, D = 2, 2, 32, 8
    ks = jax.random.split(jax.random.key(1), 3)
    q, k, v = (_rand(kk, (B, H, S, D)) for kk in ks)
    mask = np.ones((B, S), np.int32)
    mask[0, 20:] = 0
    mask[1, 5:] = 0
    bias4 = attention_bias_from_mask(jnp.asarray(mask), dtype=jnp.float32)
    dense = dot_product_attention(q, k, v, bias4)
    key_bias = bias4[:, 0, 0, :]
    ring = ring_attention_sharded(q, k, v, key_bias, _mesh(4))
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense),
                               atol=2e-5, rtol=2e-5)


def test_gradients_match_dense():
    B, H, S, D = 1, 2, 32, 8
    ks = jax.random.split(jax.random.key(2), 3)
    q, k, v = (_rand(kk, (B, H, S, D)) for kk in ks)
    mesh = _mesh(4)

    def loss_ring(q, k, v):
        return ring_attention_sharded(q, k, v, None, mesh).sum()

    def loss_dense(q, k, v):
        return dot_product_attention(q, k, v, None).sum()

    gr = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
    gd = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(gr, gd):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=3e-5, rtol=3e-5)


def test_long_sequence_memory_shape():
    # 8-way sharded 1024-seq: each chip only ever holds 128 keys
    B, H, S, D = 1, 2, 1024, 16
    ks = jax.random.split(jax.random.key(3), 3)
    q, k, v = (_rand(kk, (B, H, S, D)) for kk in ks)
    out = ring_attention_sharded(q, k, v, None, _mesh(8))
    assert out.shape == (B, H, S, D)
    dense = dot_product_attention(q, k, v, None)
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense),
                               atol=5e-5, rtol=5e-5)


def test_causal_ring_matches_dense():
    from bcfl_tpu.models.llama import causal_bias

    B, H, S, D = 1, 2, 64, 8
    ks = jax.random.split(jax.random.key(5), 3)
    q, k, v = (_rand(kk, (B, H, S, D)) for kk in ks)
    dense = dot_product_attention(q, k, v, causal_bias(jnp.ones((B, S), jnp.int32)))
    ring = ring_attention_sharded(q, k, v, None, _mesh(4), causal=True)
    np.testing.assert_allclose(np.asarray(ring), np.asarray(dense),
                               atol=2e-5, rtol=2e-5)


def test_causal_ring_with_padding():
    from bcfl_tpu.models.llama import causal_bias

    B, H, S, D = 2, 2, 64, 8
    ks = jax.random.split(jax.random.key(6), 3)
    q, k, v = (_rand(kk, (B, H, S, D)) for kk in ks)
    mask = np.ones((B, S), np.int32)
    mask[1, 40:] = 0
    dense = dot_product_attention(q, k, v, causal_bias(jnp.asarray(mask)))
    key_bias = jnp.asarray((1 - mask) * -1e30, jnp.float32)
    ring = ring_attention_sharded(q, k, v, key_bias, _mesh(4), causal=True)
    live = np.asarray(mask, bool)
    for b in range(B):
        np.testing.assert_allclose(np.asarray(ring)[b, :, live[b]],
                                   np.asarray(dense)[b, :, live[b]],
                                   atol=2e-5, rtol=2e-5)


# ----------------------- GSPMD twin (no shard_map) --------------------------

@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("n_dev", [2, 8])
def test_gspmd_twin_matches_dense_and_shard_map(causal, n_dev):
    """ring_attention_gspmd: same ring math as the shard_map impl but plain
    jit + sharding annotations (the KV roll lowers to collective-permute) —
    the SP path that is fast on platforms where shard_map is not."""
    from bcfl_tpu.models.llama import causal_bias
    from bcfl_tpu.parallel.ring_attention import ring_attention_gspmd

    B, H, S, D = 2, 2, 64, 8
    ks = jax.random.split(jax.random.key(3), 3)
    q, k, v = (_rand(kk, (B, H, S, D)) for kk in ks)
    mask = np.ones((B, S), np.int32)
    mask[0, 50:] = 0
    key_bias = jnp.where(jnp.asarray(mask) > 0, 0.0, -1e30)
    mesh = _mesh(n_dev)

    gs = jax.jit(lambda q, k, v, b: ring_attention_gspmd(
        q, k, v, b, mesh, causal=causal))(q, k, v, key_bias)

    if causal:
        bias4 = causal_bias(jnp.asarray(mask))
    else:
        bias4 = attention_bias_from_mask(jnp.asarray(mask), dtype=jnp.float32)
    dense = dot_product_attention(q, k, v, bias4)
    # compare only live query rows: fully-padded queries are garbage in
    # both impls (their outputs are masked out downstream)
    live = np.asarray(mask, bool)
    g, d = np.asarray(gs), np.asarray(dense)
    for b in range(B):
        np.testing.assert_allclose(g[:, :, live[b]][b], d[:, :, live[b]][b],
                                   atol=3e-5, rtol=3e-5)

    sm = ring_attention_sharded(q, k, v, key_bias, mesh, causal=causal)
    s = np.asarray(sm)
    for b in range(B):
        np.testing.assert_allclose(g[:, :, live[b]][b], s[:, :, live[b]][b],
                                   atol=3e-5, rtol=3e-5)


def test_gspmd_twin_gradients():
    from bcfl_tpu.parallel.ring_attention import ring_attention_gspmd

    B, H, S, D = 1, 2, 32, 8
    ks = jax.random.split(jax.random.key(4), 3)
    q, k, v = (_rand(kk, (B, H, S, D)) for kk in ks)
    mesh = _mesh(4)

    def loss_ring(q, k, v):
        return ring_attention_gspmd(q, k, v, None, mesh, causal=True).sum()

    def loss_dense(q, k, v):
        from bcfl_tpu.models.llama import causal_bias

        bias = causal_bias(jnp.ones((B, S), jnp.int32))
        return dot_product_attention(q, k, v, bias).sum()

    # grads wrt q AND k/v: dK/dV flow back through the rolled (collective-
    # permute) carry — the novel path a q-only test would miss
    g1 = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g2 = jax.grad(loss_dense, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g1, g2):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-5)
