"""Sequence-parallel llama: ring attention inside the MODEL forward.

Parity oracle: the identical parameters run through the plain dense-path
model on the same (CPU) devices. Logits and gradients must agree — the ring
merge is exact (online-softmax), not an approximation. float32 compute so
tolerances are numerical noise, not dtype rounding.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

from bcfl_tpu.models import build, get_config
from bcfl_tpu.models.llama import LlamaLM
from bcfl_tpu.parallel.sp import init_sp_lm, make_sp_lm_train_step, ring_config


def _mesh():
    devs = jax.devices()
    return Mesh(np.asarray(devs), ("seq",))


def _cfgs(seq=64):
    base = get_config("tiny-llama", dtype=jnp.float32, use_flash=False,
                      max_position=seq)
    mesh = _mesh()
    return base, ring_config(base, mesh), mesh


def _batch(seq, B=2, vocab=8192, pad_last=10):
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(4, vocab, (B, seq)), jnp.int32)
    mask = jnp.ones((B, seq), jnp.int32)
    mask = mask.at[1, seq - pad_last:].set(0)  # ragged padding
    return ids, mask


def test_sp_forward_matches_dense():
    base, ringed, mesh = _cfgs()
    ids, mask = _batch(64)
    dense_m, ring_m = LlamaLM(base), LlamaLM(ringed)
    params = dense_m.init(jax.random.key(0), ids, mask)["params"]
    want = dense_m.apply({"params": params}, ids, mask)
    got = jax.jit(lambda p, i, m: ring_m.apply({"params": p}, i, m))(
        params, ids, mask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-4, rtol=2e-4)


def test_sp_gradients_match_dense():
    base, ringed, mesh = _cfgs()
    ids, mask = _batch(64)
    dense_m, ring_m = LlamaLM(base), LlamaLM(ringed)
    params = dense_m.init(jax.random.key(1), ids, mask)["params"]

    def loss(m):
        def f(p):
            lg = m.apply({"params": p}, ids, mask)[:, :-1]
            tgt = ids[:, 1:]
            w = mask[:, 1:].astype(jnp.float32)
            import optax

            per = optax.softmax_cross_entropy_with_integer_labels(
                lg.astype(jnp.float32), tgt)
            return (per * w).sum() / w.sum()

        return f

    g_dense = jax.grad(loss(dense_m))(params)
    g_ring = jax.jit(jax.grad(loss(ring_m)))(params)
    diffs = jax.tree.map(
        lambda a, b: float(np.abs(np.asarray(a) - np.asarray(b)).max()),
        g_dense, g_ring)
    assert max(jax.tree.leaves(diffs)) < 2e-4, diffs


def test_sp_train_step_runs_and_learns():
    base, ringed, mesh = _cfgs()
    model = LlamaLM(ringed)
    step, tx = make_sp_lm_train_step(model, mesh, learning_rate=3e-3)
    params = init_sp_lm(model, mesh, batch=2, seq=64)
    opt = tx.init(params)
    ids, mask = _batch(64)
    batch = {"ids": ids, "mask": mask,
             "example_mask": jnp.ones((2,), jnp.float32)}
    losses = []
    for _ in range(5):
        params, opt, loss = step(params, opt, batch)
        losses.append(float(loss))
    assert losses[-1] < losses[0], losses


def test_sp_encoder_forward_matches_dense():
    """Non-causal ring inside the ENCODER family: TextClassifier logits
    with ring attention over 8-way seq sharding match the dense path on
    identical params, including ragged padding via the [B, S] key bias."""
    from bcfl_tpu.models.bert import TextClassifier

    mesh = _mesh()
    base = get_config("tiny-bert", dtype=jnp.float32, num_labels=3)
    ringed = ring_config(base, mesh)
    ids, mask = _batch(64, vocab=base.vocab_size)
    dense_m, ring_m = TextClassifier(base), TextClassifier(ringed)
    params = dense_m.init(jax.random.key(2), ids, mask)["params"]
    want = dense_m.apply({"params": params}, ids, mask)
    got = jax.jit(lambda p, i, m: ring_m.apply({"params": p}, i, m))(
        params, ids, mask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=2e-4, rtol=2e-4)


def test_ring_config_rejects_missing_axis():
    base = get_config("tiny-llama")
    mesh = Mesh(np.asarray(jax.devices()), ("clients",))
    with pytest.raises(ValueError, match="seq"):
        ring_config(base, mesh)


def test_build_accepts_override():
    # registry path composes: overrides flow through get_config/build
    mesh = _mesh()
    m = build("tiny-llama", head="lm",
              attention_override=ring_config(
                  get_config("tiny-llama"), mesh).attention_override)
    assert m.cfg.attention_override is not None
