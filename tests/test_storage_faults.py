"""Durable-state chaos (ROBUSTNESS.md §10, RUNTIME.md "State-sync
protocol").

What this suite pins, layer by layer:

- **FaultPlan storage lane** — seeded per-(version, peer) damage draws:
  identical coordinates always replay the identical damage class/offset,
  disarmed peers and out-of-span versions draw None, the sync-tamper
  draw fires only on the FIRST serve of a listed pair, and every
  armed-but-vacuous plan shape is rejected at construction (config-level
  gates included: no ledger root of trust, checkpointing off, local
  runtime).
- **Damage-class x classification matrix** — for EVERY class in
  ``STORAGE_CLASSES``: :func:`apply_storage_fault` on a real committed
  3-round directory produces exactly the :func:`classify_round` status
  the class models, :func:`scrub` flags it (or, for ``rollback``,
  provably can NOT — the locally-undetectable case the chain high-water
  guard exists for), the forensic :func:`restore_checkpoint` refuses the
  damaged round, and :func:`restore_latest` degrades to the previous
  intact round instead of dying.
- **Retention** — ``keep_last=K`` garbage-collects rounds (dir + meta)
  strictly beyond the newest K, only after the new round's commit.
- **Unified restore shapes** — ``restore_checkpoint`` and
  ``restore_latest`` return the same ``(round, state, ledger_json)``
  tuple (or None), pinned against drift.
- **STATE_SYNC receiver gates** — on a real ``PeerRuntime`` handler with
  a real ledger chain: a tampered payload (refingerprint mismatch), a
  tampered row (bad links), a forked history, a rolled-back server
  (both via ``forked_prefix``), a missing commitment row, and an empty
  chain are ALL refused with the right reason and leave the peer still
  bootstrapping; the honest serve is adopted, rebuilds the replica
  chain, and the captured event stream satisfies
  ``repair_authenticated`` (every adopt consumed a verified-ok).
- **The two new invariants** — ``repair_authenticated`` and
  ``no_rollback_readmission`` batch/streaming twins agree needle-by-
  needle: unauthenticated adopt fires, cross-incarnation verify does not
  authorize, high-water readmission fires, adopt/resync exemptions hold,
  same-pid shrink stays monotone_heads' jurisdiction, chain_len=None is
  ignored.
- **3-peer loopback repair** — one supervised SIGKILL + meta bit-flip +
  ``--resume --bootstrap`` rejoin end to end on CPU loopback: the scrub
  flags the damage, the repair rides a chain-verified STATE_SYNC, and
  the full invariant suite (including both new rules) is clean over the
  collated streams.
"""

from __future__ import annotations

import os
import shutil
from types import SimpleNamespace

import numpy as np
import pytest

from bcfl_tpu.checkpoint import (
    ROUND_STATUSES,
    apply_storage_fault,
    classify_round,
    restore_checkpoint,
    restore_latest,
    save_checkpoint,
    scrub,
)
from bcfl_tpu.faults import FaultPlan
from bcfl_tpu.faults.plan import STORAGE_CLASSES
from bcfl_tpu.ledger.ledger import GENESIS, Ledger, params_digest
from bcfl_tpu.telemetry.invariants import (
    no_rollback_readmission,
    repair_authenticated,
)
from bcfl_tpu.telemetry.live import (
    SNoRollbackReadmission,
    SRepairAuthenticated,
)

pytestmark = [pytest.mark.dist, pytest.mark.faults]


# ------------------------------------------------------------------ fixtures


def _state(v: float):
    return {"trainable": {"w": np.full((8, 4), v, np.float32)},
            "seed": np.int64(42)}


def _ledger_json(n: int) -> str:
    led = Ledger(True)
    for i in range(n):
        led.append(i, i % 2, {"w": np.full((4,), float(i), np.float32)})
    return led.to_json()


@pytest.fixture(scope="module")
def seed_ckpts(tmp_path_factory):
    """Three committed rounds with embedded ledgers — built once, copied
    per damage class (orbax writes dominate this suite's wall time)."""
    d = str(tmp_path_factory.mktemp("storage_seed") / "ck")
    for r in range(3):
        save_checkpoint(d, r, _state(float(r)),
                        ledger_json=_ledger_json(r + 1))
    return d


def _copy(seed: str, tmp_path) -> str:
    d = str(tmp_path / "ck")
    shutil.copytree(seed, d)
    return d


# --------------------------------------------------------- seeded draw lane


def test_storage_draws_deterministic_and_bounded():
    def mk():
        return FaultPlan(seed=5, storage_peers=(1, 2), storage_prob=0.5,
                         storage_rounds=tuple(range(1, 40)),
                         sync_tamper=((0, 1), (2, 0)))

    a, b = mk(), mk()
    grid = [(v, p) for v in range(40) for p in range(3)]
    draws = [a.storage_action(v, p) for v, p in grid]
    assert draws == [b.storage_action(v, p) for v, p in grid]
    # disarmed peer and out-of-span version draw None, always
    assert all(d is None for (v, p), d in zip(grid, draws) if p == 0)
    assert all(d is None for (v, p), d in zip(grid, draws) if v == 0)
    fired = [d for d in draws if d]
    assert fired, "armed lane never fired across 40x3 draws"
    for d in fired:
        assert d["cls"] in STORAGE_CLASSES
        assert 0.0 <= d["frac"] < 1.0
        assert d["delete_last"] == 1
    # an explicit class subset bounds the draw
    sub = FaultPlan(seed=5, storage_peers=(1,), storage_prob=1.0,
                    storage_classes=("delete", "rollback"))
    assert {sub.storage_action(v, 1)["cls"] for v in range(30)} \
        <= {"delete", "rollback"}


def test_sync_tamper_first_serve_only():
    plan = FaultPlan(seed=5, sync_tamper=((0, 1), (2, 0)))
    assert plan.storage_enabled
    t = plan.sync_tamper_action(0, 1, 0)
    assert t is not None and 0.0 <= t["frac"] < 1.0
    # deterministic across constructions; serial>0 and unlisted pairs None
    assert t == FaultPlan(seed=5,
                          sync_tamper=((0, 1), (2, 0))).sync_tamper_action(
                              0, 1, 0)
    assert plan.sync_tamper_action(0, 1, 1) is None
    assert plan.sync_tamper_action(1, 0, 0) is None
    assert plan.sync_tamper_action(2, 0, 0) is not None


def test_vacuous_storage_plans_rejected():
    with pytest.raises(ValueError):
        FaultPlan(seed=1, storage_peers=(0,))          # prob 0: never fires
    with pytest.raises(ValueError):
        FaultPlan(seed=1, storage_prob=0.5, storage_rounds=())
    with pytest.raises(ValueError):
        FaultPlan(seed=1, storage_rounds=(2,))         # span without prob
    with pytest.raises(ValueError):
        FaultPlan(seed=1, storage_prob=0.5, storage_classes=("bogus",))
    with pytest.raises(ValueError):
        FaultPlan(seed=1, storage_prob=0.5, storage_classes=())
    with pytest.raises(ValueError):
        FaultPlan(seed=1, storage_delete_last=0)
    with pytest.raises(ValueError):
        FaultPlan(seed=1, sync_tamper=((0, 0),))       # self-pair
    with pytest.raises(ValueError):
        FaultPlan(seed=1, sync_tamper=((0, 1), (0, 1)))  # duplicate
    with pytest.raises(ValueError):
        FaultPlan(seed=1, storage_prob=1.5)


def test_config_storage_lane_gates():
    from bcfl_tpu.config import (
        DistConfig,
        FedConfig,
        LedgerConfig,
        PartitionConfig,
    )

    base = dict(dataset="synthetic", model="tiny-bert", num_clients=4,
                num_rounds=2, seq_len=16, batch_size=4, max_local_batches=2,
                partition=PartitionConfig(kind="iid", iid_samples=8))
    dist_base = dict(runtime="dist", mode="server", sync="async",
                     eval_every=0)
    faults = FaultPlan(seed=1, storage_peers=(0,), storage_prob=0.5)
    # the lane is dist-only (RUNTIME_CAPS): local runtime rejected
    with pytest.raises(ValueError, match="storage"):
        FedConfig(**base, faults=faults, ledger=LedgerConfig(enabled=True))
    # no ledger: no root of trust for the repair path
    with pytest.raises(ValueError, match="root of trust"):
        FedConfig(**base, **dist_base, faults=faults,
                  dist=DistConfig(peers=2))
    # checkpointing off: the lane would silently never fire
    with pytest.raises(ValueError, match="never"):
        FedConfig(**base, **dist_base, faults=faults,
                  ledger=LedgerConfig(enabled=True),
                  dist=DistConfig(peers=2, checkpoint_every_versions=0))
    # storage_peers / sync_tamper ids must exist in the fleet
    with pytest.raises(ValueError, match="storage_peers"):
        FedConfig(**base, **dist_base, ledger=LedgerConfig(enabled=True),
                  faults=FaultPlan(seed=1, storage_peers=(5,),
                                   storage_prob=0.5),
                  dist=DistConfig(peers=2))
    with pytest.raises(ValueError, match="sync_tamper"):
        FedConfig(**base, **dist_base, ledger=LedgerConfig(enabled=True),
                  faults=FaultPlan(seed=1, sync_tamper=((0, 7),)),
                  dist=DistConfig(peers=2))
    with pytest.raises(ValueError):
        DistConfig(checkpoint_keep_last=-1)
    ok = FedConfig(**base, **dist_base, faults=faults,
                   ledger=LedgerConfig(enabled=True),
                   dist=DistConfig(peers=2, checkpoint_keep_last=3))
    assert ok.faults.storage_enabled
    assert ok.dist.checkpoint_keep_last == 3


# ------------------------------------------------- damage x classification


# every class damages round 2 of the 3-round seed dir; the statuses a
# class may legally produce (payload damage can land as an unrestorable
# tree OR as a digest mismatch depending on where the byte sits)
_EXPECTED = {
    "torn": ("missing",),
    "payload_flip": ("unrestorable", "digest_mismatch"),
    "meta_flip": ("digest_mismatch",),
    "truncate": ("unrestorable", "digest_mismatch"),
    "delete": ("deleted",),
    "ledger": ("ledger_corrupt",),
    "rollback": ("missing",),
}


@pytest.mark.parametrize("cls", STORAGE_CLASSES)
def test_damage_class_classification(cls, seed_ckpts, tmp_path):
    assert set(_EXPECTED) == set(STORAGE_CLASSES)
    d = _copy(seed_ckpts, tmp_path)
    rec = apply_storage_fault(d, {"cls": cls, "frac": 0.4, "delete_last": 1})
    assert rec is not None and rec["cls"] == cls and rec["round"] == 2
    status, state, ledger_json = classify_round(d, 2)
    assert status in ROUND_STATUSES
    assert status in _EXPECTED[cls], (cls, status)
    assert state is None and ledger_json is None
    # the forensic single-round read refuses damaged state outright
    assert restore_checkpoint(d, 2) is None
    rep = scrub(d)
    if cls == "rollback":
        # locally undetectable BY DESIGN: dir+meta removed cleanly, an
        # older intact snapshot left as the apparent newest — only the
        # chain high-water guard / no_rollback_readmission can see it
        assert not rep["damaged"] and not rep["torn"]
        assert rep["newest_intact"] == 1
    elif cls == "torn":
        assert rep["torn"], rep
        assert rep["newest_intact"] == 1
    else:
        assert any(r == 2 and s == status for r, s in rep["damaged"]), rep
        assert rep["newest_intact"] == 1
    assert not rep["empty"]
    # bounded fallback: every class leaves round 1 intact and restorable
    got = restore_latest(d)
    assert got is not None
    r, st, lj = got
    assert r == 1
    np.testing.assert_array_equal(
        st["trainable"]["w"], np.full((8, 4), 1.0, np.float32))
    assert Ledger.from_json(lj).verify_chain() == -1


def test_scrub_clean_and_empty(seed_ckpts, tmp_path):
    rep = scrub(seed_ckpts)
    assert not rep["damaged"] and not rep["torn"] and not rep["empty"]
    assert rep["newest_intact"] == 2
    assert [r for r, _s in rep["rounds"]] == [0, 1, 2]
    empty = scrub(str(tmp_path / "nothing_here"))
    assert empty["empty"] and empty["newest_intact"] is None


# ----------------------------------------------------------------- retention


def test_retention_keeps_only_newest_k(tmp_path):
    d = str(tmp_path / "ck")
    for r in range(5):
        save_checkpoint(d, r, _state(float(r)),
                        ledger_json=_ledger_json(r + 1), keep_last=2)
    rep = scrub(d)
    # dirs AND metas beyond the newest 2 are gone (scrub unions both
    # listings, so a leftover meta would surface as a "deleted" round)
    assert [r for r, _s in rep["rounds"]] == [3, 4]
    assert not rep["damaged"] and rep["newest_intact"] == 4
    got = restore_latest(d)
    assert got is not None and got[0] == 4
    # keep_last=0 keeps everything
    d0 = str(tmp_path / "ck0")
    for r in range(4):
        save_checkpoint(d0, r, _state(float(r)), keep_last=0)
    assert [r for r, _s in scrub(d0)["rounds"]] == [0, 1, 2, 3]
    # GC is ordered after commit: even keep_last=1 always leaves the
    # just-committed round restorable
    d1 = str(tmp_path / "ck1")
    for r in range(3):
        save_checkpoint(d1, r, _state(float(r)), keep_last=1)
        got = restore_latest(d1)
        assert got is not None and got[0] == r


def test_restore_shapes_unified(seed_ckpts):
    latest = restore_latest(seed_ckpts)
    one = restore_checkpoint(seed_ckpts, 2)
    assert isinstance(latest, tuple) and len(latest) == 3
    assert isinstance(one, tuple) and len(one) == 3
    r, st, lj = one
    assert (r, latest[0]) == (2, 2)
    np.testing.assert_array_equal(st["trainable"]["w"],
                                  latest[1]["trainable"]["w"])
    assert lj == latest[2] and lj is not None
    # absent round: None, no fallback (the forensic contract)
    assert restore_checkpoint(seed_ckpts, 7) is None


# ------------------------------------------------- STATE_SYNC receiver gates


def _mk_runtime(chain):
    """A PeerRuntime shell with exactly the state `_handle_state_sync`
    reads — no sockets, no mesh; the adopt-side engine hooks are
    identity stubs."""
    from bcfl_tpu.dist.runtime import PeerRuntime

    rt = PeerRuntime.__new__(PeerRuntime)
    rt.peer_id = 1
    rt.cfg = SimpleNamespace(
        ledger=SimpleNamespace(use_native=True),
        dist=SimpleNamespace(checkpoint_every_versions=0),
        param_dtype="float32")
    rt.chain = chain
    rt.eng = SimpleNamespace(ledger=chain,
                             mesh=SimpleNamespace(replicate=lambda t: t))
    rt.rep = None
    rt.trainable = None
    rt.version = 0
    rt.adopted = []
    rt._needs_bootstrap = True
    rt._bootstrap_reason = "damaged"
    rt._last_sync_req = 99.0
    rt._cast = lambda t: t
    rt._note_version = lambda: None
    return rt


def _server_rows(model, version=3, server=0, n=4):
    led = Ledger(True)
    for i in range(n):
        led.append_digest(i, i % 2, bytes([i + 1]) * 32, 64)
    led.commit_state(version, server, params_digest(model, True))
    return led


def _recv_chain(rows, upto):
    led = Ledger(True)
    assert led.append_rows(rows[:upto]) == -1
    return led


def test_state_sync_gates_refuse_and_adopt(tmp_path):
    from bcfl_tpu import telemetry as T
    from bcfl_tpu.telemetry import read_stream

    model = {"w": np.arange(32, dtype=np.float32).reshape(8, 4)}
    server = _server_rows(model)
    rows = server.segment(0)

    stream = str(tmp_path / "events_peer1.jsonl")
    T.install(T.EventWriter(stream, peer=1, run="needles"))
    try:
        # tampered payload: refingerprint != committed state row
        rt = _mk_runtime(_recv_chain(rows, 2))
        bad = {"model": {"w": model["w"] + 1.0}}
        rt._handle_state_sync({"from": 0, "version": 3, "chain": rows}, bad)
        assert rt._needs_bootstrap and rt._last_sync_req == 0.0

        # tampered row: the segment no longer verifies from genesis
        forged = [dict(r) for r in rows]
        forged[1]["digest"] = ("ab" * 32)
        rt = _mk_runtime(_recv_chain(rows, 2))
        rt._handle_state_sync({"from": 0, "version": 3, "chain": forged},
                              {"model": model})
        assert rt._needs_bootstrap

        # forked history: receiver's surviving prefix disagrees
        alt = Ledger(True)
        alt.append_digest(0, 99, b"\x77" * 32, 64)
        rt = _mk_runtime(alt)
        rt._handle_state_sync({"from": 0, "version": 3, "chain": rows},
                              {"model": model})
        assert rt._needs_bootstrap

        # rolled-back server: serves a strict PREFIX of what the receiver
        # still durably holds — same forked_prefix gate, rollback flavor
        rt = _mk_runtime(_recv_chain(rows, len(rows)))
        rt._handle_state_sync({"from": 0, "version": 3, "chain": rows[:3]},
                              {"model": model})
        assert rt._needs_bootstrap

        # no commitment row for the claimed (version, server)
        rt = _mk_runtime(_recv_chain(rows, 2))
        rt._handle_state_sync({"from": 0, "version": 9, "chain": rows},
                              {"model": model})
        assert rt._needs_bootstrap

        # empty chain
        rt = _mk_runtime(_recv_chain(rows, 2))
        rt._handle_state_sync({"from": 0, "version": 3, "chain": []},
                              {"model": model})
        assert rt._needs_bootstrap

        # the honest serve: adopted, replica rebuilt, repair recorded
        rt = _mk_runtime(_recv_chain(rows, 2))
        rt._handle_state_sync({"from": 0, "version": 3, "chain": rows},
                              {"model": model})
        assert not rt._needs_bootstrap
        assert rt.version == 3 and rt.adopted == [3]
        assert len(rt.chain) == len(rows)
        assert rt.chain.verify_chain() == -1
        assert rt.eng.ledger is rt.chain
        assert rt._repaired == {"from": 0, "version": 3,
                                "reason": "damaged"}
        # a late serve after the repair is audited through the same gates
        # (its refusal lands in the stream as durable evidence) but is
        # never adopted and never re-enters the request cycle
        v_before = rt.version
        rt._last_sync_req = 99.0
        rt._handle_state_sync({"from": 0, "version": 4, "chain": rows},
                              {"model": model})
        assert rt.version == v_before and rt.adopted == [3]
        assert rt._last_sync_req == 99.0
    finally:
        T.uninstall()

    events, _meta = read_stream(stream)
    refusals = [e for e in events if e["ev"] == "state.sync.refuse"]
    assert [e["reason"] for e in refusals] == [
        "digest_mismatch", "bad_links", "forked_prefix", "forked_prefix",
        "no_commitment", "no_chain", "no_commitment"]
    verdicts = [e["ok"] for e in events if e["ev"] == "state.sync.verify"]
    assert verdicts == [False] * 6 + [True, False]
    adopts = [e for e in events if e["ev"] == "state.sync.adopt"]
    assert len(adopts) == 1 and adopts[0]["version"] == 3
    assert adopts[0]["chain_len"] == len(rows)
    # the captured stream itself satisfies the authentication invariant:
    # the one adopt consumed the one verified-ok
    assert repair_authenticated(events) == []
    # ...and a doctored copy with the verify stripped fires it
    doctored = [e for e in events if not (e["ev"] == "state.sync.verify"
                                          and e.get("ok"))]
    fired = repair_authenticated(doctored)
    assert len(fired) == 1
    assert fired[0]["rule"] == "repair_authenticated"


# ----------------------------------------- invariant needles (batch==stream)


def _ev(ev, pid, seq, **fields):
    return {"v": 1, "ev": ev, "run": "fx", "peer": 1, "pid": pid,
            "seq": seq, "t_wall": float(seq), "t_mono": float(seq),
            **fields}


def _needles():
    """(name, events, expected repair_authenticated fires, expected
    no_rollback_readmission fires)."""
    cases = []
    cases.append(("unauthenticated_adopt",
                  [_ev("state.sync.adopt", 10, 0, version=3, src=0)], 1, 0))
    cases.append(("authenticated_adopt",
                  [_ev("state.sync.verify", 10, 0, ok=True, src=0),
                   _ev("state.sync.adopt", 10, 1, version=3, src=0)], 0, 0))
    cases.append(("failed_verify_does_not_authorize",
                  [_ev("state.sync.verify", 10, 0, ok=False, src=0),
                   _ev("state.sync.adopt", 10, 1, version=3, src=0)], 1, 0))
    cases.append(("cross_incarnation_verify_rejected",
                  [_ev("state.sync.verify", 10, 0, ok=True, src=0),
                   _ev("state.sync.adopt", 20, 0, version=3, src=0)], 1, 0))
    cases.append(("rollback_readmission",
                  [_ev("ckpt.save", 10, 0, step=3, chain_len=6, gc=0),
                   _ev("ckpt.save", 20, 0, step=1, chain_len=2, gc=0)],
                  0, 1))
    cases.append(("readmission_exempt_via_adopt",
                  [_ev("ckpt.save", 10, 0, step=3, chain_len=6, gc=0),
                   _ev("state.sync.verify", 20, 0, ok=True, src=0),
                   _ev("state.sync.adopt", 20, 1, version=1, src=0),
                   _ev("ckpt.save", 20, 2, step=1, chain_len=2, gc=0)],
                  0, 0))
    cases.append(("readmission_exempt_via_resync",
                  [_ev("ckpt.save", 10, 0, step=3, chain_len=6, gc=0),
                   _ev("ledger", 20, 0, op="resync", chain_len=2,
                       rewrite=True, head8="aa"),
                   _ev("ckpt.save", 20, 1, step=1, chain_len=2, gc=0)],
                  0, 0))
    # a SAME-pid shrink is monotone_heads' jurisdiction, not this rule's
    cases.append(("same_pid_shrink_out_of_scope",
                  [_ev("ckpt.save", 10, 0, step=3, chain_len=6, gc=0),
                   _ev("ckpt.save", 10, 1, step=1, chain_len=2, gc=0)],
                  0, 0))
    # ledgerless checkpoints carry chain_len=None and are never judged
    cases.append(("chain_len_none_ignored",
                  [_ev("ckpt.save", 10, 0, step=3, chain_len=6, gc=0),
                   _ev("ckpt.save", 20, 0, step=1, chain_len=None, gc=0)],
                  0, 0))
    # forward progress across incarnations is clean
    cases.append(("forward_rejoin_clean",
                  [_ev("ckpt.save", 10, 0, step=3, chain_len=6, gc=0),
                   _ev("ckpt.save", 20, 0, step=4, chain_len=8, gc=0)],
                  0, 0))
    return cases


@pytest.mark.parametrize("name,events,ra,nrr",
                         [(c[0], c[1], c[2], c[3]) for c in _needles()],
                         ids=[c[0] for c in _needles()])
def test_invariant_needles_batch_and_streaming_agree(name, events, ra, nrr):
    batch_ra = repair_authenticated(events)
    batch_nrr = no_rollback_readmission(events)
    assert len(batch_ra) == ra, (name, batch_ra)
    assert len(batch_nrr) == nrr, (name, batch_nrr)
    s_ra, s_nrr = SRepairAuthenticated(), SNoRollbackReadmission()
    for e in events:
        s_ra.feed(e)
        s_nrr.feed(e)
    assert s_ra.finalize() == batch_ra, name
    assert s_nrr.finalize() == batch_nrr, name


# ------------------------------------------------------ loopback integration


def test_three_peer_loopback_storage_repair(tmp_path):
    """The tentpole end to end on CPU loopback (~60 s): peer 2 SIGKILLed
    once a checkpoint exists, its newest meta sidecar bit-flipped while
    it is down, restarted with --resume --bootstrap. Gates: the startup
    scrub flags the damage; the fallback restore trips the chain
    high-water guard into bootstrap; the repair is a chain-verified
    STATE_SYNC adopt; the fleet completes; and the whole invariant suite
    — repair_authenticated and no_rollback_readmission included — is
    clean over the collated streams."""
    from bcfl_tpu.config import (
        DistConfig,
        FedConfig,
        LedgerConfig,
        PartitionConfig,
    )
    from bcfl_tpu.dist.harness import run_dist
    from bcfl_tpu.telemetry import collate, read_stream

    cfg = FedConfig(
        name="storage_loopback", runtime="dist", mode="server",
        sync="async", model="tiny-bert", dataset="synthetic",
        num_clients=6, num_rounds=4, seq_len=16, batch_size=4,
        max_local_batches=2, eval_every=0, seed=42,
        partition=PartitionConfig(kind="iid", iid_samples=8),
        ledger=LedgerConfig(enabled=True),
        # quorum_frac=0.9: the leader refuses to advance while the
        # damaged peer is DOWN — it must still be serving when the
        # bootstrapper comes back asking for STATE_SYNC
        dist=DistConfig(peers=3, buffer_timeout_s=8.0, idle_timeout_s=90.0,
                        peer_deadline_s=280.0, checkpoint_every_versions=1,
                        checkpoint_keep_last=2, suspect_after=1,
                        quorum_frac=0.9),
    )
    run_dir = str(tmp_path / "storage_loopback")
    res = run_dist(cfg, run_dir, deadline_s=320.0, platform="cpu",
                   churn={"peer": 2, "cycles": 1, "period_s": 5.0,
                          "downtime_s": 1.0, "stop_after_s": 150.0,
                          "damage": ["meta_flip"], "bootstrap": True})
    assert res["ok"], (res["returncodes"], res["log_tails"])
    assert res["churn"], "the supervised kill never fired"
    assert (res["churn"][0].get("damage") or {}).get("cls") == "meta_flip", \
        res["churn"]
    evs = [e for p in res["event_streams"] for e in read_stream(p)[0]]
    assert any(e["ev"] == "scrub" and e.get("status") == "damaged"
               for e in evs), "the bit-flip never surfaced in a scrub"
    assert any(e["ev"] == "state.sync.verify" and e.get("ok")
               for e in evs), "no chain-verified transfer"
    adopts = [e for e in evs if e["ev"] == "state.sync.adopt"]
    assert adopts, "the damaged peer never adopted a repair"
    col = collate(res["event_streams"])
    assert col["ok"], col["violations"]
    assert col["invariants"]["repair_authenticated"] == 0
    assert col["invariants"]["no_rollback_readmission"] == 0
