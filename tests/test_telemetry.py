"""Telemetry layer (bcfl_tpu.telemetry, OBSERVABILITY.md) — tier-1.

Three contracts, each pinned here because the dist chaos proofs GATE on
them:

1. **Event schema round-trip + crash tolerance** — typed events survive
   the writer -> stream -> reader path bit-intact; a torn final line (the
   SIGKILL signature) is tolerated and counted, never raised.
2. **Causal collation** — the merged timeline orders a send before the
   recv it caused even when the receiver's wall clock is skewed BEHIND
   the sender's (the cross-host case wall-sorting gets wrong), while
   preserving each stream's own seq order.
3. **Invariants fire** — every declared invariant detects its seeded
   corruption (double-merge, lost acked frame, cross-partition merge,
   quarantine without evidence, shrinking chain) and stays silent on the
   clean twin. A check that cannot fail is not a check.
"""

import json
import os

import numpy as np
import pytest

from bcfl_tpu import telemetry as T

pytestmark = pytest.mark.telemetry


# ------------------------------------------------------------------ helpers


def _ev(ev, peer, seq, t, pid=None, **fields):
    """A hand-built stream event (what EventWriter would have written)."""
    rec = {"v": 1, "ev": ev, "run": "fx", "peer": peer,
           "pid": pid if pid is not None else 1000 + (hash(peer) % 7),
           "seq": seq, "t_wall": t, "t_mono": t}
    rec.update(fields)
    return rec


def _send(peer, seq, t, to, msg_id, epoch=1, ok=True, mtype="update"):
    return _ev("send", peer, seq, t, to=to, type=mtype, ok=ok,
               msg_id=msg_id, msg_epoch=epoch, attempts=1, wall_s=0.01)


def _recv(peer, seq, t, src, msg_id, epoch=1, disposition="accepted"):
    return _ev("recv", peer, seq, t, src=src, msg_epoch=epoch,
               msg_id=msg_id, disposition=disposition, type="update")


def _merge(peer, seq, t, version, arrivals, component=(0, 1, 2),
           **kw):
    return _ev("merge", peer, seq, t, version=version, leader=peer,
               arrivals=arrivals, rejected=[], solo=False, degraded=False,
               component=list(component), wall_s=0.01, **kw)


def _end(peer, seq, t):
    return _ev("run.end", peer, seq, t, status="ok")


def _arrival(peer, msg_id, epoch=1, staleness=0, weight=1.0):
    return {"peer": peer, "msg_id": msg_id, "msg_epoch": epoch,
            "staleness": staleness, "latency_s": 0.01, "weight": weight}


# ------------------------------------------------- writer / reader contract


def test_event_schema_roundtrip(tmp_path):
    path = str(tmp_path / "events_peer0.jsonl")
    w = T.EventWriter(path, peer=0, run="rt", flush_every=2)
    w.emit("run.start", role="peer", peers=3)
    w.emit("send", to=1, type="update", ok=True, msg_id=7, msg_epoch=2,
           attempts=1, bytes=123, wall_s=0.25, t_wall=1234.5)
    w.emit("merge", version=1, leader=0,
           arrivals=[_arrival(1, 0, staleness=2, weight=3.5)],
           rejected=[], solo=False, degraded=False, component=[0, 1],
           wall_s=0.1, chain_len=4, head8="ab", rewrite=False)
    w.emit("run.end", status="ok")
    w.close()

    events, meta = T.read_stream(path)
    assert not meta["torn_tail"] and meta["corrupt_lines"] == 0
    assert [e["seq"] for e in events] == [0, 1, 2, 3]
    assert [e["ev"] for e in events] == ["run.start", "send", "merge",
                                        "run.end"]
    send = events[1]
    # stamps: hybrid time + identity fields survive exactly, and the
    # explicit t_wall override (the send START instant) is honored
    assert send["t_wall"] == 1234.5 and "t_mono" in send
    assert (send["peer"], send["to"], send["msg_epoch"], send["msg_id"]) \
        == (0, 1, 2, 7)
    m = events[2]
    assert m["arrivals"][0]["staleness"] == 2
    assert m["arrivals"][0]["weight"] == 3.5
    assert m["chain_len"] == 4 and m["rewrite"] is False


def test_writer_drops_bad_events_never_raises(tmp_path):
    w = T.EventWriter(str(tmp_path / "e.jsonl"), peer=0)
    w.emit("not.a.type", x=1)            # unknown type
    w.emit("send", to=1)                 # missing required fields
    w.emit("phase", name="x", wall_s=object())  # unserializable -> str()
    w.close()
    events, _ = T.read_stream(str(tmp_path / "e.jsonl"))
    assert w.dropped == 2
    assert [e["ev"] for e in events] == ["phase"]


def test_numpy_values_serialize(tmp_path):
    w = T.EventWriter(str(tmp_path / "e.jsonl"), peer=0)
    w.emit("round", round=np.int64(3), wall_s=np.float32(0.5),
           extra=np.arange(3))
    w.close()
    (e,), _ = T.read_stream(str(tmp_path / "e.jsonl"))
    assert e["round"] == 3 and e["extra"] == [0, 1, 2]


def test_torn_tail_and_corrupt_lines_tolerated(tmp_path):
    path = str(tmp_path / "events_peer1.jsonl")
    w = T.EventWriter(path, peer=1)
    for r in range(5):
        w.emit("round", round=r, wall_s=0.1)
    w.close()
    raw = open(path, "rb").read().splitlines(keepends=True)
    # corrupt a MIDDLE line (disk damage) and tear the FINAL one (SIGKILL
    # mid-write): the reader must yield every other event and count both
    raw[2] = b'{"v": 1, "ev": "round", GARBAGE\n'
    raw.append(b'{"v":1,"ev":"round","pee')  # no newline: torn
    with open(path, "wb") as f:
        f.writelines(raw)
    events, meta = T.read_stream(path)
    assert meta["torn_tail"] is True
    assert meta["corrupt_lines"] == 1
    assert [e["round"] for e in events] == [0, 1, 3, 4]
    # and the collator consumes the same stream without raising
    col = T.collate([path])
    assert col["torn_tails"] == 1
    assert col["timeline"]["per_peer"]["1"]["rounds"] == 4


def test_append_reopen_terminates_torn_tail(tmp_path):
    # a restarted incarnation reopens the stream in append mode: the
    # predecessor's torn final line must be newline-terminated first, or
    # the restart's first event would be glued onto it and lost
    path = str(tmp_path / "events_peer1.jsonl")
    w = T.EventWriter(path, peer=1)
    w.emit("round", round=0, wall_s=0.1)
    w.close()
    with open(path, "ab") as f:
        f.write(b'{"v":1,"ev":"round","pee')  # SIGKILL mid-write
    w2 = T.EventWriter(path, peer=1)
    w2.emit("run.start", role="peer")
    w2.close()
    events, meta = T.read_stream(path)
    assert [e["ev"] for e in events] == ["round", "run.start"]
    # the terminated torn line is now mid-file: counted, not fatal
    assert meta["corrupt_lines"] == 1 and meta["torn_tail"] is False


def test_sampling_deterministic_and_exact_at_extremes(tmp_path):
    w1 = T.EventWriter(str(tmp_path / "a.jsonl"), peer=0, sample=0.5)
    w2 = T.EventWriter(str(tmp_path / "b.jsonl"), peer=0, sample=0.5)
    keys = [(0, 1, i, 0) for i in range(200)]
    picked1 = [k for k in keys if w1.sampled(k)]
    picked2 = [k for k in keys if w2.sampled(k)]
    assert picked1 == picked2          # deterministic across writers
    assert 0 < len(picked1) < len(keys)  # actually samples
    w1.sample = 0.0
    assert not any(w1.sampled(k) for k in keys)
    w1.sample = 1.0
    assert all(w1.sampled(k) for k in keys)
    w1.close()
    w2.close()


# -------------------------------------------------------- causal collation


def test_causal_order_repairs_skewed_clocks():
    """Receiver clock 40s BEHIND the sender: wall sort would put the recv
    (and the merge it fed) before the send. The happens-before edges must
    repair that while keeping each stream's own seq order."""
    send = _send("A", seq=1, t=100.0, to="B", msg_id=9)
    pre = _ev("run.start", "A", 0, 99.0, role="peer")
    # B's stream, 40s skewed: recv at t=60, merge at t=61
    recv = _recv("B", seq=0, t=60.0, src="A", msg_id=9)
    merge = _merge("B", seq=1, t=61.0, version=1,
                   arrivals=[_arrival("A", 9)])
    ordered = T.causal_order([merge, recv, send, pre])
    pos = {(e["ev"], e.get("peer")): i for i, e in enumerate(ordered)}
    assert pos[("send", "A")] < pos[("recv", "B")]
    assert pos[("recv", "B")] < pos[("merge", "B")]
    assert pos[("run.start", "A")] < pos[("send", "A")]


def test_causal_order_preserves_per_stream_seq():
    evs = [_ev("round", "P", seq=s, t=100.0 - s, round=s, wall_s=0.1)
           for s in range(6)]  # wall times REVERSED vs seq
    ordered = T.causal_order(list(reversed(evs)))
    assert [e["seq"] for e in ordered] == list(range(6))


def test_summarize_latency_staleness_lineage():
    events = [
        _send("A", 0, 10.0, to="B", msg_id=0),
        _send("A", 1, 11.0, to="B", msg_id=1),
        _recv("B", 0, 10.5, src="A", msg_id=0),
        _recv("B", 1, 12.0, src="A", msg_id=1),
        _recv("B", 2, 12.1, src="A", msg_id=1, disposition="dedup"),
        _merge("B", 3, 13.0, version=1,
               arrivals=[_arrival("A", 0, staleness=0, weight=2.0),
                         _arrival("A", 1, staleness=3, weight=1.0)]),
    ]
    s = T.summarize(T.causal_order(events))
    # only ACCEPTED deliveries measure latency: the dedup recv of msg 1
    # is the duplicate's arrival, not delivery, and must not skew p95
    assert s["message_latency_s"]["n"] == 2
    assert abs(s["message_latency_s"]["max"] - 1.0) < 1e-9
    assert s["staleness"] == {"0": 1, "3": 1}
    assert s["merges"] == {"count": 1, "arrivals": 2,
                           "unique_update_ids": 2, "rejected": 0,
                           "solo": 0, "degraded": 0}
    assert s["per_peer"]["B"]["recv"] == {"accepted": 2, "dedup": 1}


# --------------------------------------------------------------- invariants


def _clean_run():
    """A minimal 2-peer fixture that satisfies every invariant."""
    return [
        _send("A", 0, 10.0, to="B", msg_id=0),
        _recv("B", 0, 10.2, src="A", msg_id=0),
        _merge("B", 1, 11.0, version=1, arrivals=[_arrival("A", 0)],
               component=["A", "B"], chain_len=2, head8="aa",
               rewrite=False),
        _merge("B", 2, 12.0, version=2, arrivals=[_arrival("A", 1)],
               component=["A", "B"], chain_len=4, head8="bb",
               rewrite=False),
        _send("A", 1, 11.5, to="B", msg_id=1),
        _recv("B", 3, 11.7, src="A", msg_id=1),
        _end("A", 2, 20.0),
        _end("B", 4, 20.0),
    ]


def test_invariants_clean_fixture_all_pass():
    out = T.run_invariants(T.causal_order(_clean_run()))
    assert set(out) == set(T.INVARIANTS)
    assert all(v == [] for v in out.values()), out


def test_double_merge_detected():
    events = _clean_run()
    # seed the corruption: version 2 re-merges update (A, epoch 1, msg 0)
    events[3]["arrivals"] = [_arrival("A", 0)]
    out = T.run_invariants(T.causal_order(events))
    assert len(out["no_double_merge"]) == 1
    v = out["no_double_merge"][0]
    assert v["first_version"] == 1 and v["second_version"] == 2
    # the SAME identity re-merged by a different leader incarnation
    # (append-mode streams: a re-run restarts epoch/msg_id counters) is
    # not a dedup failure — scoped by leader pid
    remerge = _merge("B", 0, 30.0, version=1, arrivals=[_arrival("A", 0)],
                     component=["A", "B"], chain_len=2, head8="aa",
                     rewrite=False)
    remerge["pid"] = 99999
    out_fresh = T.run_invariants(T.causal_order(_clean_run() + [remerge]))
    assert out_fresh["no_double_merge"] == []
    # an identity-less arrival is a violation of the same rule
    events[3]["arrivals"] = [{"peer": "A", "staleness": 0}]
    out = T.run_invariants(T.causal_order(events))
    assert any("identity" in v["problem"]
               for v in out["no_double_merge"])


def test_lost_acked_frame_detected_only_on_clean_close():
    events = _clean_run()
    del events[5]  # B never saw msg 1, yet A recorded it acked
    out = T.run_invariants(T.causal_order(events))
    assert len(out["acked_not_lost"]) == 1
    assert out["acked_not_lost"][0]["msg_id"] == 1
    # without B's clean close the same loss is NOT judged (a SIGKILLed
    # receiver's unflushed tail proves nothing)
    events2 = [e for e in events if not (e["ev"] == "run.end"
                                         and e["peer"] == "B")]
    out2 = T.run_invariants(T.causal_order(events2))
    assert out2["acked_not_lost"] == []
    # a receiver with TWO pids (killed + restarted incarnations) is not
    # judged either, even with a run.end
    events3 = [dict(e) for e in events]
    for e in events3:
        if e["peer"] == "B" and e["seq"] >= 3:
            e["pid"] = 4242
    out3 = T.run_invariants(T.causal_order(events3))
    assert out3["acked_not_lost"] == []
    # grace is judged against the send's END (start + wall_s): a chaos-
    # retried send that only got acked AFTER the receiver's final flush
    # may legitimately miss the receiver's stream
    events4 = [dict(e) for e in events]
    del events4[5]  # the recv is again missing...
    for e in events4:
        if e["ev"] == "send" and e.get("msg_id") == 1:
            e["wall_s"] = 30.0  # ...but the ack landed way past B's close
    out4 = T.run_invariants(T.causal_order(events4))
    assert out4["acked_not_lost"] == []


def test_causal_order_survives_real_writer_cycle():
    # sends are emitted AFTER the ack (late seq), so a chaos dup that
    # delivers early + a merge broadcast returning before the sender's
    # retry loop records its send closes a genuine 4-cycle:
    #   send_A -> recv_B -> send_B -> recv_A -> (A seq) -> send_A
    events = [
        _ev("recv", "A", 1, 11.2, src="B", msg_id=5, msg_epoch=1,
            disposition="accepted"),
        _send("A", 3, 10.0, to="B", msg_id=0),     # emitted last on A
        _recv("B", 0, 10.1, src="A", msg_id=0),
        _send("B", 1, 11.0, to="A", msg_id=5),
    ]
    ordered = T.causal_order(events)
    assert len(ordered) == 4  # nothing dropped, no hang
    # per-stream seq order is ground truth and must survive the break
    a_seqs = [e["seq"] for e in ordered if e["peer"] == "A"]
    b_seqs = [e["seq"] for e in ordered if e["peer"] == "B"]
    assert a_seqs == sorted(a_seqs) and b_seqs == sorted(b_seqs)


def test_causal_order_restart_cannot_overtake_dead_incarnation():
    # B's original incarnation recvs a frame whose send A only records at
    # ack time, seconds later (chaos retries). Without a dead->restart
    # edge the original's seq chain stalls on that cross edge while the
    # restart's events sail past it in the wall-time heap — the restart's
    # LONGER checkpoint then precedes the original's shorter one and
    # no_rollback_readmission reports a phantom rollback (seen live in
    # the gossip partition soak under wire chaos + churn).
    send = _send("A", 1, 970.0, to="B", msg_id=9)     # stamped at ack
    recv = _recv("B", 1, 900.0, src="A", msg_id=9)
    recv["pid"] = 111
    events = [
        _ev("round", "A", 0, 100.0, pid=send["pid"], round=0, wall_s=0.1),
        send,
        _ev("run.start", "B", 0, 890.0, pid=111, role="peer"),
        recv,
        _ev("ckpt.save", "B", 2, 940.0, pid=111, chain_len=26,
            round=5, wall_s=0.1),
        _ev("ckpt.save", "B", 0, 968.0, pid=222, chain_len=36,
            round=10, wall_s=0.1),                    # the restart
    ]
    ordered = T.causal_order(events)
    saves = [(e["pid"], e["chain_len"]) for e in ordered
             if e["ev"] == "ckpt.save"]
    assert saves == [(111, 26), (222, 36)]
    out = T.run_invariants(ordered)
    assert out["no_rollback_readmission"] == []


def test_cross_partition_merge_detected():
    events = _clean_run()
    events[2]["component"] = ["B", "C"]  # A is outside the leader's side
    out = T.run_invariants(T.causal_order(events))
    assert len(out["no_cross_partition_merge"]) == 1
    assert out["no_cross_partition_merge"][0]["from_peer"] == "A"


def test_quarantine_without_evidence_detected():
    base = _clean_run()
    trans = _ev("rep.transition", "B", 5, 13.0, client=2, trust=0.1,
                **{"from": "suspect", "to": "quarantined"})
    out = T.run_invariants(T.causal_order(base + [trans]))
    assert len(out["quarantine_evidence"]) == 1
    # with prior evidence in the same stream the transition is legal
    evid = _ev("rep.evidence", "B", 4, 12.5, client=2, fault=1.0)
    trans2 = dict(trans, seq=6)
    out2 = T.run_invariants(T.causal_order(base + [evid, trans2]))
    assert out2["quarantine_evidence"] == []
    # a from="restored" re-declaration is exempt WITHOUT local evidence:
    # a resumed follower replays quarantines it absorbed from the
    # leader's committed chain rows — the evidence lives in the leader's
    # stream, not its own (exposed by the dist_soak churn lane)
    restored = _ev("rep.transition", "B", 5, 13.0, client=2, trust=0.3,
                   scope="peer",
                   **{"from": "restored", "to": "quarantined"})
    out3 = T.run_invariants(T.causal_order(base + [restored]))
    assert out3["quarantine_evidence"] == []


def test_shrinking_chain_detected_and_rewrite_exempt():
    events = _clean_run()
    shrink = _ev("ledger", "B", 5, 14.0, op="append", chain_len=1,
                 rewrite=False, head8="cc")
    out = T.run_invariants(T.causal_order(events + [shrink]))
    assert len(out["monotone_heads"]) == 1
    assert out["monotone_heads"][0]["prev_len"] == 4
    # the same shrink flagged as a declared rewrite (fork-merge adoption /
    # full resync) is legal
    rewrite = dict(shrink, op="resync", rewrite=True)
    out2 = T.run_invariants(T.causal_order(events + [rewrite]))
    assert out2["monotone_heads"] == []
    # a NEW process incarnation (append-mode streams: a re-run into the
    # same dir, a within-run restart) starts its own baseline — its short
    # fresh chain is not a shrink of its predecessor's
    fresh = dict(_ev("ledger", "B", 0, 30.0, op="commit", chain_len=1,
                     rewrite=False, head8="dd"), pid=99999)
    out3 = T.run_invariants(T.causal_order(events + [fresh]))
    assert out3["monotone_heads"] == []


# -------------------------------------------------------- global emit seam


def test_global_emit_is_noop_until_installed(tmp_path):
    T.uninstall()
    T.emit("round", round=0, wall_s=0.1)  # must not raise, writes nowhere
    path = str(tmp_path / "events_engine.jsonl")
    T.install(T.EventWriter(path, peer=None, run="g"))
    T.emit("round", round=1, wall_s=0.1)
    T.uninstall()
    events, _ = T.read_stream(path)
    assert [e["round"] for e in events] == [1]


def test_collate_run_over_directory(tmp_path):
    for p in (0, 1):
        w = T.EventWriter(str(tmp_path / f"events_peer{p}.jsonl"), peer=p)
        w.emit("run.start", role="peer", peers=2)
        w.emit("run.end", status="ok")
        w.close()
    col = T.collate_run(str(tmp_path))
    assert len(col["streams"]) == 2
    assert col["ok"] and col["invariant_violations_total"] == 0
    assert len(col["ordered"]) == 4


# --------------------------------------------- local engine end-to-end


def test_engine_streams_events_and_collates(tmp_path):
    """A real (tiny) local engine run with telemetry_dir set: the stream
    carries run lifecycle, per-round spans, StepClock phases, ledger
    commits with monotone chain growth, reputation evidence, and
    checkpoint saves — and the collator finds zero invariant violations."""
    import tests.conftest  # noqa: F401  (8-device CPU mesh)
    from bcfl_tpu.config import FedConfig, LedgerConfig, PartitionConfig
    from bcfl_tpu.fed.engine import FedEngine
    from bcfl_tpu.faults import FaultPlan
    from bcfl_tpu.reputation import ReputationConfig

    tdir = str(tmp_path / "tel")
    cfg = FedConfig(
        name="tel_engine", dataset="synthetic", model="tiny-bert",
        num_clients=4, num_rounds=3, seq_len=16, batch_size=4,
        max_local_batches=2, mode="server", eval_every=0,
        partition=PartitionConfig(kind="iid", iid_samples=8),
        ledger=LedgerConfig(enabled=True),
        reputation=ReputationConfig(enabled=True),
        faults=FaultPlan(seed=3, flaky_clients=(1,), flaky_burst_len=1,
                         flaky_on_prob=1.0),
        checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=2,
        telemetry_dir=tdir)
    FedEngine(cfg).run()

    assert T.get_writer() is None  # run() uninstalled its writer
    col = T.collate_run(tdir)
    assert col["ok"], col["violations"]
    ordered = col["ordered"]
    kinds = [e["ev"] for e in ordered]
    assert kinds[0] == "run.start" and kinds[-1] == "run.end"
    assert ordered[-1]["status"] == "ok"
    assert kinds.count("round") == cfg.num_rounds
    # StepClock phases stream as typed spans
    names = {e["name"] for e in ordered if e["ev"] == "phase"}
    assert {"control_plane", "round_program", "ledger"} <= names
    # ledger commits: one per round, chain strictly growing
    commits = [e for e in ordered
               if e["ev"] == "ledger" and e["op"] == "commit"]
    assert len(commits) == cfg.num_rounds
    lens = [e["chain_len"] for e in commits]
    assert lens == sorted(lens) and lens[-1] == 4 * cfg.num_rounds
    # the flaky corrupter produced reputation evidence events
    assert any(e["ev"] == "rep.evidence" and e["client"] == 1
               for e in ordered)
    assert any(e["ev"] == "ckpt.save" for e in ordered)


# ------------------------------------------------------- ResourceMonitor fix


def test_resource_monitor_primed_baseline():
    """The first psutil cpu_percent call always returns a meaningless 0.0;
    the monitor must discard it (priming) rather than store it as a
    'before' reading, and snapshot() must return the windowed value."""
    from bcfl_tpu.metrics import ResourceMonitor

    m = ResourceMonitor()
    assert not hasattr(m, "cpu_before")  # the bogus stored 0.0 is gone
    sum(i * i for i in range(200_000))   # burn a little CPU in-window
    snap = m.snapshot()
    assert snap["cpu_percent"] >= 0.0
    assert snap["latency_min"] >= 0.0
