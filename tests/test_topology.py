"""Golden tests of the topology layer against the reference notebooks' fixed
10-node graph (the hard-coded edges of All_graphs_IMDB_dataset.ipynb cell 2
are a ready-made fixture — SURVEY.md §4)."""

import numpy as np
import pytest

from bcfl_tpu.topology import (
    REFERENCE_BANDWIDTH_MBPS,
    anomaly_filter,
    reference_graph,
    random_graph,
)
from bcfl_tpu.topology.filters import FILTERS, pagerank_scores
from bcfl_tpu.topology.graph import metropolis_mixing_matrix

MT_MODEL_GB = 0.40362595301121473  # MT notebook cell 23
BCFL_GB = 0.043  # MT notebook cell 27


def test_reference_matrix_shape_and_range():
    bw = REFERENCE_BANDWIDTH_MBPS
    assert bw.shape == (10, 10)
    off = bw[~np.eye(10, dtype=bool)]
    assert off.min() == 88 and off.max() == 496  # notebook's stated range


def test_pagerank_matches_networkx_oracle():
    nx = pytest.importorskip("networkx")
    g = reference_graph()
    w = g.edge_weights()
    G = nx.DiGraph()
    for i in range(10):
        for j in range(10):
            if i != j:
                G.add_edge(str(i), str(j), weight=w[i, j])
    want = np.array([nx.pagerank(G, weight="weight")[str(i)] for i in range(10)])
    got = pagerank_scores(g)
    np.testing.assert_allclose(got, want, atol=1e-6)


def test_pagerank_anomalies_golden():
    # mean +- 1 sigma outliers of weighted PageRank on the notebook graph
    anomalies, _ = FILTERS["pagerank"](reference_graph())
    assert anomalies == [0, 4, 7, 9]


def test_dbscan_finds_none_on_reference_graph():
    # eps=300 against degrees of order 0.03: one big cluster (faithful to
    # notebook cell 4's parameters)
    anomalies, deg = FILTERS["dbscan"](reference_graph())
    assert anomalies == []
    assert deg.shape == (10,)


def test_dbscan_flags_with_sane_eps():
    # with an eps on the data's scale the filter actually works
    g = reference_graph()
    from bcfl_tpu.topology.filters import dbscan_filter

    anomalies, _ = dbscan_filter(g, eps=0.002, min_samples=2)
    assert isinstance(anomalies, list)  # runs; membership depends on scale


def test_zscore_anomalies_golden():
    anomalies, z = FILTERS["zscore"](reference_graph())
    assert anomalies == [8, 9]
    assert (np.abs(z[anomalies]) > 1).all()


def test_community_filter_runs():
    anomalies, member = FILTERS["community"](reference_graph())
    assert anomalies == []  # greedy modularity puts every node somewhere
    assert (member >= 0).all()


def test_worked_example_edge_times():
    """MT nb cell 23: t(1->2) = 0.4036 GB / 145 = 2.7 s; t(1->3) = 1.17 s.
    (The notebook quotes direct-link times; relaying 1->3->2 is actually
    cheaper, which shortest_path_times correctly exploits.)"""
    g = reference_graph()
    direct = MT_MODEL_GB * 1000.0 * g.edge_weights()
    assert direct[1, 2] == pytest.approx(403.62595 / 145, rel=1e-6)
    assert direct[1, 2] == pytest.approx(2.78, abs=0.01)
    assert direct[1, 3] == pytest.approx(1.177, abs=0.01)
    times = g.shortest_path_times(MT_MODEL_GB)
    assert (times[1] <= direct[1] + 1e-12).all()
    assert times[1, 2] == pytest.approx(2.239, abs=0.01)  # via node 3


def test_sync_async_and_filter_ordering():
    """Headline claims (README.md:10): async cuts info-passing time by ~76%;
    PageRank is the most effective filter (notebook ordering
    pagerank < zscore < dbscan for post-filter sync time)."""
    g = reference_graph()
    sync, asyn = g.info_passing_time(MT_MODEL_GB, source=1)
    assert asyn < sync
    assert (sync - asyn) / sync > 0.70  # reference claims 76%

    results = {}
    for name in ["dbscan", "zscore", "pagerank"]:
        d = anomaly_filter(name, g, protect=(1,))
        s, a = g.info_passing_time(MT_MODEL_GB, source=1, anomalies=d["anomalies"])
        results[name] = (s, a)
    assert results["pagerank"][0] < results["zscore"][0] < results["dbscan"][0]


def test_bcfl_payload_scales_times():
    """BC-FL: same model with the 0.043 GB ledger payload (MT nb cell 27) —
    times scale by exactly the payload ratio on a fixed graph."""
    g = reference_graph()
    s_full, a_full = g.info_passing_time(MT_MODEL_GB, source=1)
    s_bc, a_bc = g.info_passing_time(BCFL_GB, source=1)
    ratio = BCFL_GB / MT_MODEL_GB
    assert s_bc == pytest.approx(s_full * ratio, rel=1e-9)
    assert a_bc == pytest.approx(a_full * ratio, rel=1e-9)


def test_source_in_anomalies_raises_and_protect_works():
    g = reference_graph()
    with pytest.raises(ValueError):
        g.info_passing_time(MT_MODEL_GB, source=0, anomalies=[0])
    d = anomaly_filter("pagerank", g, protect=(0,))
    assert 0 not in d["anomalies"]
    assert d["mask"][0] == 1.0


def test_random_graph_and_filters_scale_to_other_sizes():
    g = random_graph(16, seed=3)
    for name in FILTERS:
        d = anomaly_filter(name, g)
        assert d["mask"].shape == (16,)
        assert set(np.unique(d["mask"])) <= {0.0, 1.0}


def test_metropolis_matrix_doubly_stochastic_with_mask():
    mask = np.ones(8)
    mask[2] = 0
    W = metropolis_mixing_matrix(mask)
    np.testing.assert_allclose(W.sum(1), 1.0, atol=1e-12)
    np.testing.assert_allclose(W.sum(0), 1.0, atol=1e-12)
    assert W[2, 2] == 1.0 and W[2, :2].sum() == 0 and W[:, 2].sum() == 1.0
    # consensus: W^k x -> mean over participants
    x = np.arange(8.0)
    y = np.linalg.matrix_power(W, 200) @ x
    participants = [i for i in range(8) if i != 2]
    np.testing.assert_allclose(y[participants], x[participants].mean(), atol=1e-6)
