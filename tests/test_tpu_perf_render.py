"""tpu_perf evidence-preservation machinery: per-row artifact merging and
the PERF.md auto-section rewrite. These guard the invariant that a
transient failure (wedge, RPC error, missing artifact) can never SHADOW
previously recorded silicon evidence — only a clean fresh row may replace
a recorded one. Pure host-side (no backend), millisecond-fast."""

import importlib.util
import json
import os


_SPEC = importlib.util.spec_from_file_location(
    "tpu_perf", os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "scripts", "tpu_perf.py"))
tp = importlib.util.module_from_spec(_SPEC)
_SPEC.loader.exec_module(tp)


BENCH_ROW = "| 32 | 8 | 942.47 | 113.1 | 40.23 |"
PERF_FIXTURE = "\n".join([
    tp.AUTO_BEGIN,
    "# PERF",
    "",
    "## Fed fine-tune throughput vs dispatch shape",
    "",
    "| rounds/dispatch | steps/round | samples/s/chip | vs baseline | MFU % |",
    "|---|---|---|---|---|",
    BENCH_ROW,
    "",
    "## Flash attention kernels (B=2, H=12, D=64, causal, bf16)",
    "",
    "| seq | pallas fwd ms | xla fwd ms | pallas bwd ms | xla bwd ms | "
    "dense fwd ms | fwd max-abs-err vs XLA | bwd max-abs-err | ok |",
    "|---|---|---|---|---|---|---|---|---|",
    "| 512 | 1.0 | 1.1 | 2.0 | 2.1 | — | 1.0e-03 | 1.0e-02 | PASS |",
    "",
    "Reproduce: x",
    tp.AUTO_END,
    "",
    "hand-written analysis below the marker",
    "",
])


def _write_fixture(tmp_path):
    p = tmp_path / "PERF.md"
    p.write_text(PERF_FIXTURE)
    return str(p)


# ---- _merge_rows ---------------------------------------------------------

def test_merge_no_prior_artifact(tmp_path):
    rows = [{"seq": 512, "pallas_fwd_ms": 1}, {"seq": 1024, "error": "x"}]
    out = tp._merge_rows(list(rows), str(tmp_path / "missing.json"), "seq")
    assert out == rows


def test_merge_prior_rescues_fresh_error_and_keeps_extra_seqs(tmp_path):
    prior = tmp_path / "prior.json"
    prior.write_text(json.dumps([
        {"seq": 512, "pallas_fwd_ms": 99},
        {"seq": 1024, "pallas_fwd_ms": 7},
        {"seq": 4096, "pallas_fwd_ms": 3}]))
    out = tp._merge_rows(
        [{"seq": 512, "pallas_fwd_ms": 1}, {"seq": 1024, "error": "rpc"}],
        str(prior), "seq")
    assert [r["seq"] for r in out] == [512, 1024, 4096]
    assert out[0]["pallas_fwd_ms"] == 1      # fresh clean wins
    assert out[1]["pallas_fwd_ms"] == 7      # prior clean rescues fresh error
    assert out[2]["pallas_fwd_ms"] == 3      # prior-only seq kept


def test_merge_tuple_key_and_dict_wrapped_artifact(tmp_path):
    prior = tmp_path / "prior.json"
    prior.write_text(json.dumps(
        {"source": "s", "rows": [{"rounds": 1, "steps": 4, "value": 621}]}))
    out = tp._merge_rows(
        [{"rounds": 1, "steps": 4, "error": "timeout"},
         {"rounds": 32, "steps": 8, "value": 942}],
        str(prior), ("rounds", "steps"))
    assert out[0]["value"] == 621 and out[1]["value"] == 942


def test_merge_prior_error_does_not_rescue(tmp_path):
    prior = tmp_path / "prior.json"
    prior.write_text(json.dumps([{"seq": 512, "error": "old"}]))
    out = tp._merge_rows([{"seq": 512, "error": "new"}], str(prior), "seq")
    assert out[0]["error"] == "new"


# ---- write_perf_md preservation -----------------------------------------

def test_empty_rows_preserve_both_recorded_tables(tmp_path):
    path = _write_fixture(tmp_path)
    tp.write_perf_md("TPU v5 lite", [], "B=2, H=12, D=64", [], None,
                     path=path)
    text = open(path).read()
    assert BENCH_ROW in text
    assert "| 512 | 1.0 | 1.1 |" in text
    assert "hand-written analysis below the marker" in text


def test_failed_sweep_keeps_prev_header_and_notes_failure(tmp_path):
    path = _write_fixture(tmp_path)
    tp.write_perf_md("TPU v5 lite", [], "FAILED: ImportError: boom", [],
                     None, path=path)
    text = open(path).read()
    assert "kernels (FAILED" not in text          # no failure banner header
    assert "kernels (B=2, H=12, D=64" in text     # previous shape kept
    assert "previously recorded rows kept" in text
    assert "| 512 | 1.0 | 1.1 |" in text


def test_failed_sweep_with_no_prior_rows_does_not_claim_preservation(
        tmp_path):
    path = str(tmp_path / "PERF.md")  # no existing file at all
    tp.write_perf_md("TPU v5 lite", [], "FAILED: RuntimeError: x", [],
                     None, path=path)
    text = open(path).read()
    assert "no previously recorded rows" in text
    assert "previously recorded rows kept" not in text


def test_compression_rows_render_and_placeholder(tmp_path):
    """The --compress sweep table: fresh rows render with MB formatting +
    ratio; with nothing recorded the explicit placeholder appears (never a
    silently absent section — the axis must be visible even before the
    first TPU window runs it)."""
    path = _write_fixture(tmp_path)
    tp.write_perf_md(
        "TPU v5 lite", [], "B=2, H=12, D=64", [], None,
        comp_rows=[{"compress": "int8+topk", "value": 900.0,
                    "bytes_on_wire_per_round": 27e6,
                    "bytes_raw_per_round": 438e6,
                    "compression_ratio": 16.2},
                   {"compress": "topk", "error": "wedge"}],
        path=path)
    text = open(path).read()
    assert "| int8+topk | 900.0 | 27.0 MB | 438.0 MB | 16.2 |" in text
    assert "| topk | ERROR: wedge |" in text
    assert BENCH_ROW in text  # other tables still preserved
    p2 = str(tmp_path / "P2.md")
    tp.write_perf_md("TPU v5 lite", [], "B=1", [], None, path=p2)
    assert "no rows recorded yet" in open(p2).read()


def test_fresh_rows_replace_tables_and_drop_failure_note(tmp_path):
    path = _write_fixture(tmp_path)
    tp.write_perf_md(
        "TPU v5 lite",
        [{"value": 1, "vs_baseline": 2, "mfu_pct": 3,
          "rounds": 1, "steps": 4}],
        "B=2, H=12, D=64",
        [{"seq": 2048, "pallas_fwd_ms": 5.0, "xla_fwd_ms": 5.1,
          "pallas_bwd_ms": 6.0, "xla_bwd_ms": 6.1,
          "fwd_max_abs_err": 1e-3, "bwd_max_abs_err": 1e-2,
          "numerics_ok": True}],
        None, path=path)
    text = open(path).read()
    assert "| 2048 | 5.0 | 5.1 |" in text
    assert "previously recorded rows" not in text
    assert "| 1 | 4 | 1 | 2 | 3 |" in text
    assert "hand-written analysis below the marker" in text
