"""Wire-chaos + self-healing transport unit layer (marker ``dist``,
tier-1): hostile-input fuzzing of the frame format (a malformed or
corrupted stream must raise a clean WireError/CrcError within its deadline
— never a hang, never a partial tree), the at-least-once delivery contract
(retry/backoff, per-sender dedup window, failure-detector circuit
breaker), the bounded inbox, the seeded wire fault lane, and the static
"every socket op has a deadline" guard. The live multi-process proof is
``scripts/dist_chaos.py`` -> ``results/dist_chaos.json``."""

import os
import socket
import struct
import threading
import time
import zlib

import numpy as np
import pytest

from bcfl_tpu.config import DistConfig
from bcfl_tpu.dist.harness import free_ports
from bcfl_tpu.dist.transport import (
    DOWN,
    REACHABLE,
    SUSPECT,
    FailureDetector,
    PeerTransport,
    WireChaos,
)
from bcfl_tpu.dist.wire import (
    MAGIC,
    MAX_FRAME,
    PREFIX_LEN,
    CrcError,
    WireError,
    frame_prefix,
    pack_frame,
    read_frame,
    unpack_frame,
    unpack_tree,
    write_frame,
)
from bcfl_tpu.faults import FaultPlan

pytestmark = pytest.mark.dist

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ------------------------------------------------------------ fuzz helpers


def _read_expecting(raw: bytes, exc):
    """read_frame over a one-shot TCP stream of ``raw`` must raise ``exc``
    well inside its deadline — the fuzz contract: clean error, no hang."""
    srv = socket.socket()
    srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
    srv.bind(("127.0.0.1", 0))
    srv.listen(1)
    port = srv.getsockname()[1]

    def sender():
        s = socket.create_connection(("127.0.0.1", port), timeout=5.0)
        s.sendall(raw)
        s.close()

    t = threading.Thread(target=sender, daemon=True)
    t.start()
    srv.settimeout(5.0)
    conn, _ = srv.accept()
    try:
        t0 = time.time()
        with pytest.raises(exc):
            read_frame(conn, timeout_s=3.0)
        assert time.time() - t0 < 5.0
    finally:
        conn.close()
        srv.close()
        t.join()


# ------------------------------------------------------------------- fuzz


def test_fuzz_truncated_length_prefix():
    # the stream dies mid-u64: clean WireError, not garbage or a hang
    _read_expecting(MAGIC + b"\x01\x02\x03", WireError)


def test_fuzz_oversize_length_rejected_before_allocation():
    raw = MAGIC + struct.pack("<Q", MAX_FRAME + 1) + b"\x00" * 16
    _read_expecting(raw, WireError)


def test_fuzz_garbage_header_json():
    payload = struct.pack("<I", 9) + b"not json!" + struct.pack("<I", 0)
    raw = (MAGIC + struct.pack("<Q", len(payload))
           + struct.pack("<I", zlib.crc32(payload)) + payload)
    _read_expecting(raw, WireError)
    # and the direct unpack path agrees
    with pytest.raises(WireError, match="JSON"):
        unpack_frame(payload)


def test_fuzz_header_not_an_object():
    hdr = b"[1, 2, 3]"
    payload = (struct.pack("<I", len(hdr)) + hdr + struct.pack("<I", 0))
    with pytest.raises(WireError, match="expected an object"):
        unpack_frame(payload)


def test_fuzz_flipped_payload_byte_is_crc_error():
    frame = bytearray(pack_frame({"type": "update", "n": 1},
                                 {"t": {"x": np.float32([1, 2, 3, 4])}}))
    frame[PREFIX_LEN + 7] ^= 0xFF
    _read_expecting(bytes(frame), CrcError)


def test_fuzz_mid_tree_truncation():
    # index declares 48 body bytes; only 40 arrive — the leaf must not
    # half-materialize
    index = (b'[{"path": "x", "dtype": "<f4", "shape": [3, 4]}]')
    with pytest.raises(WireError, match="truncated"):
        unpack_tree(index, b"\x00" * 40)
    # trailing garbage after the last leaf is equally malformed
    with pytest.raises(WireError, match="trailing"):
        unpack_tree(index, b"\x00" * 50)


@pytest.mark.parametrize("index", [
    b'[{"path": "x", "dtype": "garbage", "shape": [2]}]',
    b'[{"path": "x", "dtype": "<f4", "shape": [-1]}]',
    b'[{"path": "x", "dtype": "<f4", "shape": "oops"}]',
    b'[{"dtype": "<f4", "shape": [2]}]',
    b'{"not": "a list"}',
    b'[42]',
    b'[{"path": "x", "dtype": "<f8", "shape": [99999999, 99999999]}]',
    # dim past int64: np.prod raises OverflowError, which must classify
    # as WireError, not kill the serve thread (r11 review catch)
    b'[{"path": "x", "dtype": "<f8", "shape": [18446744073709551616]}]',
])
def test_fuzz_hostile_tree_index_rows(index):
    with pytest.raises(WireError):
        unpack_tree(index, b"\x00" * 16)


def test_fuzz_truncated_frame_payload_everywhere():
    # chop a valid payload at every prefix length: always WireError (or a
    # valid shorter parse — impossible here since lengths self-describe)
    payload = pack_frame({"a": 1}, {"t": {"x": np.int8([1, 2, 3])}})[
        PREFIX_LEN:]
    for cut in range(len(payload)):
        with pytest.raises(WireError):
            unpack_frame(payload[:cut])


# ------------------------------------------------- streaming wire (r11)


def _capture_stream(write_fn) -> bytes:
    """Run ``write_fn(sock)`` against a socketpair and return every byte
    it wrote."""
    a, b = socket.socketpair()
    buf = bytearray()
    done = threading.Event()

    def rd():
        b.settimeout(5.0)
        try:
            while True:
                c = b.recv(1 << 16)
                if not c:
                    break
                buf.extend(c)
        except OSError:
            pass
        done.set()

    t = threading.Thread(target=rd, daemon=True)
    t.start()
    try:
        write_fn(a)
    finally:
        a.close()
    done.wait(6.0)
    b.close()
    return bytes(buf)


def _feed(raw: bytes):
    """A socket delivering exactly ``raw`` then EOF (for read_frame)."""
    a, b = socket.socketpair()

    def wr():
        try:
            a.sendall(raw)
        except OSError:
            pass
        a.close()

    t = threading.Thread(target=wr, daemon=True)
    t.start()
    return b


_STREAM_TREES = {
    "t": {"a": {"x": np.arange(12, dtype=np.float32).reshape(3, 4),
                "y": np.float64(3.5)},
          "b": np.arange(7, dtype=np.int8)},
    "u": {"z": np.ones((2, 2), np.float32)},
}
_STREAM_HDR = {"type": "update", "from": 1, "msg_id": 5}


def test_streamed_frame_bytes_identical():
    """write_frame (chunked, zero-copy, incremental CRC) must put the
    EXACT same bytes on the wire as the in-memory reference pack_frame —
    the on-wire layout is unchanged, so ledger digests, dedup ids, and
    every PR 8 contract hold."""
    ref = pack_frame(_STREAM_HDR, _STREAM_TREES)
    got = _capture_stream(
        lambda s: write_frame(s, _STREAM_HDR, _STREAM_TREES))
    assert got == ref
    # and the prefix helper (the retry loop's one-CRC-per-logical-send
    # seam) agrees with the reference prefix
    assert frame_prefix(_STREAM_HDR, _STREAM_TREES) == ref[:PREFIX_LEN]
    # a reused prefix skips the CRC pass but streams the same bytes
    got2 = _capture_stream(
        lambda s: write_frame(s, _STREAM_HDR, _STREAM_TREES,
                              prefix=ref[:PREFIX_LEN]))
    assert got2 == ref


def test_streaming_reader_roundtrips_reference_frame():
    ref = pack_frame(_STREAM_HDR, _STREAM_TREES)
    sock = _feed(ref)
    try:
        header, trees = read_frame(sock, timeout_s=5.0)
    finally:
        sock.close()
    assert header == _STREAM_HDR
    np.testing.assert_array_equal(trees["t"]["a"]["x"],
                                  _STREAM_TREES["t"]["a"]["x"])
    y = trees["t"]["a"]["y"]
    # the wire has ALWAYS promoted 0-d scalars to (1,) (pack_tree's
    # ascontiguousarray) — the streaming reader reproduces that exactly
    assert y.shape == (1,) and y.dtype == np.float64 and float(y[0]) == 3.5
    np.testing.assert_array_equal(trees["u"]["z"],
                                  _STREAM_TREES["u"]["z"])


def test_streaming_reader_truncation_at_every_chunk_boundary():
    """Cut the byte stream at EVERY offset of a valid frame: the
    streaming reader must raise a clean WireError (or classify to
    CrcError) well inside its deadline — never a hang, never a partial
    tree returned."""
    frame = pack_frame({"n": 1}, {"t": {"x": np.int8([1, 2, 3]),
                                        "y": np.float32([1.5])}})
    for cut in range(len(frame)):
        sock = _feed(frame[:cut])
        t0 = time.time()
        try:
            with pytest.raises(WireError):
                read_frame(sock, timeout_s=2.0)
            assert time.time() - t0 < 3.0, f"cut {cut} overran deadline"
        finally:
            sock.close()


def test_streamed_crc_classification_everywhere():
    """Flip ONE payload byte at every offset: the streaming reader parses
    before the whole-frame CRC can be known, so it must classify parse
    failures by draining + finishing the CRC — in-flight damage is ALWAYS
    a CrcError (crc_drops, the retry-healable counter), wherever the flip
    lands (header JSON, length word, index, body)."""
    frame = bytearray(pack_frame(_STREAM_HDR, {"t": {"x": np.float32(
        [1, 2, 3, 4])}}))
    for pos in range(PREFIX_LEN, len(frame)):
        bad = bytearray(frame)
        bad[pos] ^= 0xFF
        sock = _feed(bytes(bad))
        try:
            with pytest.raises(CrcError):
                read_frame(sock, timeout_s=2.0)
        finally:
            sock.close()


def test_streaming_reader_hostile_lengths_never_allocate():
    """A hostile index (well-formed CRC!) declaring a leaf far larger than
    the frame carries must be rejected as WireError — crucially BEFORE the
    receiver allocates the declared size (a 4 GiB np.empty per hostile
    frame would be a memory DoS the old whole-payload reader was immune
    to). Not a CrcError: the bytes arrived exactly as sent."""
    import json as _json

    idx = _json.dumps([{"path": "x", "dtype": "<f8",
                        "shape": [1 << 28]}]).encode()
    hdr = _json.dumps({"type": "update"}).encode()
    payload = (struct.pack("<I", len(hdr)) + hdr + struct.pack("<I", 1)
               + struct.pack("<I", 1) + b"n"
               + struct.pack("<I", len(idx)) + idx
               + struct.pack("<Q", 16) + b"\x00" * 16)
    frame = (MAGIC + struct.pack("<Q", len(payload))
             + struct.pack("<I", zlib.crc32(payload)) + payload)
    sock = _feed(frame)
    try:
        with pytest.raises(WireError) as ei:
            read_frame(sock, timeout_s=3.0)
        assert not isinstance(ei.value, CrcError)
    finally:
        sock.close()
    # a declared body_len overrunning the payload is equally rejected
    payload2 = (struct.pack("<I", len(hdr)) + hdr + struct.pack("<I", 1)
                + struct.pack("<I", 1) + b"n"
                + struct.pack("<I", len(idx)) + idx
                + struct.pack("<Q", 1 << 40))
    frame2 = (MAGIC + struct.pack("<Q", len(payload2))
              + struct.pack("<I", zlib.crc32(payload2)) + payload2)
    sock = _feed(frame2)
    try:
        with pytest.raises(WireError) as ei:
            read_frame(sock, timeout_s=3.0)
        assert not isinstance(ei.value, CrcError)
    finally:
        sock.close()


def test_streamed_corrupt_frac_matches_flip_positions():
    """The writer's chaos-corruption hook flips the same payload offsets
    the pre-streaming _flip_payload_bytes did: min(int(f*n), n-1), past
    the prefix — pinned so the seeded chaos lane's draws stay replayable
    across the refactor."""
    ref = bytearray(pack_frame(_STREAM_HDR, _STREAM_TREES))
    n = len(ref) - PREFIX_LEN
    fracs = [0.0, 0.5, 0.999999]
    for f in fracs:
        ref[PREFIX_LEN + min(int(f * n), n - 1)] ^= 0xFF
    got = _capture_stream(
        lambda s: write_frame(s, _STREAM_HDR, _STREAM_TREES,
                              corrupt_frac=fracs))
    assert got == bytes(ref)


# -------------------------------------------------- detector + retry seam


def test_failure_detector_state_machine():
    det = FailureDetector(2, suspect_after=2, down_after=4,
                          probe_interval_s=30.0)
    assert det.state_of(1) == REACHABLE
    det.on_failure(1)
    assert det.state_of(1) == REACHABLE  # one failure is not suspicion
    det.on_failure(1)
    assert det.state_of(1) == SUSPECT
    det.on_failure(1)
    det.on_failure(1)
    assert det.state_of(1) == DOWN
    assert det.allow(1) is True   # the first probe is granted...
    assert det.allow(1) is False  # ...and reserves the interval
    det.on_success(1)
    assert det.state_of(1) == REACHABLE and det.allow(1)
    hops = [(t["from"], t["to"]) for t in det.transitions]
    assert hops == [(REACHABLE, SUSPECT), (SUSPECT, DOWN),
                    (DOWN, REACHABLE)]


def _policy(**kw):
    base = dict(peers=2, send_retries=2, retry_base_s=0.01,
                retry_max_s=0.05, send_deadline_s=3.0, suspect_after=1,
                down_after=3, probe_interval_s=30.0)
    base.update(kw)
    return DistConfig(**base)


def test_send_retries_then_circuit_opens_and_recovers():
    ports = free_ports(2)
    addrs = [("127.0.0.1", p) for p in ports]
    # one logical send = 3 attempts (send_retries=2): the first send ends
    # SUSPECT (3 consecutive failures < down_after=5), the second DOWN.
    # probe_interval_s also bounds the budget of sends to SUSPECT/DOWN
    # peers, so it must leave room for the retries (refused connects are
    # instant; backoffs sum to ~0.03 s here)
    a = PeerTransport(0, addrs,
                      policy=_policy(probe_interval_s=0.5, down_after=5))
    # nothing listens on the destination: every attempt is refused fast
    t0 = time.time()
    assert a.send(1, {"type": "ping"}) is False
    assert time.time() - t0 < 3.0  # bounded by the budget, not a hang
    assert a.retries == 2 and a.send_failures == 1
    assert a.detector.state_of(1) == SUSPECT
    assert a.send(1, {"type": "ping"}) is False
    assert a.detector.state_of(1) == DOWN
    # circuit open with probes always due (interval 0): sends still run,
    # still fail fast; with a long interval they are skipped instantly
    a.policy = _policy(probe_interval_s=60.0)
    a.detector.probe_interval_s = 60.0
    a.detector.allow(1)  # burn the due probe
    n = a.circuit_skips
    t0 = time.time()
    assert a.send(1, {"type": "ping"}) is False
    assert a.circuit_skips == n + 1 and time.time() - t0 < 0.1
    # the peer comes up: the next granted probe heals the circuit
    b = PeerTransport(1, addrs)
    b.start()
    try:
        a.detector.probe_interval_s = 0.001
        time.sleep(0.01)
        assert a.send(1, {"type": "ping"}) is True
        assert a.detector.state_of(1) == REACHABLE
        hops = [(t["from"], t["to"]) for t in a.detector.transitions]
        assert (REACHABLE, SUSPECT) in hops and (DOWN, REACHABLE) in hops
    finally:
        b.close()


def test_dedup_window_drops_duplicate_msg_ids():
    ports = free_ports(2)
    addrs = [("127.0.0.1", p) for p in ports]
    b = PeerTransport(1, addrs, policy=_policy(dedup_window=8))
    b.start()
    try:
        frame = pack_frame({"type": "ping", "from": 0, "msg_id": 5}, None)
        for _ in range(3):  # the same (from, msg_id) delivered thrice
            s = socket.create_connection(("127.0.0.1", ports[1]),
                                         timeout=5.0)
            s.settimeout(5.0)
            s.sendall(frame)
            assert s.recv(4) == b"BCFA"  # acked: delivered is delivered
            s.close()
        deadline = time.time() + 5.0
        while b.dups_dropped < 2 and time.time() < deadline:
            time.sleep(0.05)
        assert b.recv(1.0) is not None  # exactly one copy surfaced
        assert b.recv(0.3) is None
        assert b.dups_dropped == 2
        # an id far below the window is treated as a stale retransmit
        old = pack_frame({"type": "ping", "from": 0, "msg_id": 900}, None)
        s = socket.create_connection(("127.0.0.1", ports[1]), timeout=5.0)
        s.sendall(old)
        s.close()
        assert b.recv(1.0) is not None
        stale = pack_frame({"type": "ping", "from": 0, "msg_id": 1}, None)
        s = socket.create_connection(("127.0.0.1", ports[1]), timeout=5.0)
        s.sendall(stale)
        s.close()
        assert b.recv(0.5) is None and b.dups_dropped == 3
    finally:
        b.close()


def test_crc_valid_hostile_header_fields_are_counted_drops():
    # CRC is integrity, not authentication: a well-CRC'd frame can still
    # carry hostile field TYPES. The serving thread must count-and-drop,
    # never die with an uncaught exception (the frame is acked — delivered
    # — but handled as garbage).
    ports = free_ports(2)
    addrs = [("127.0.0.1", p) for p in ports]
    b = PeerTransport(1, addrs, policy=_policy())
    b.start()
    try:
        for bad in ({"type": "ping", "from": "abc"},
                    {"type": "ping", "from": 0, "msg_id": "xyz"},
                    {"type": "ping", "from": 0, "msg_id": 1,
                     "msg_epoch": {"not": "an int"}},
                    {"type": "ping", "from": 0, "msg_id": 2,
                     "chaos_hold_s": "soon"}):
            s = socket.create_connection(("127.0.0.1", ports[1]),
                                         timeout=5.0)
            s.settimeout(5.0)
            s.sendall(pack_frame(bad, None))
            assert s.recv(4) == b"BCFA"
            s.close()
        deadline = time.time() + 5.0
        while b.wire_drops < 4 and time.time() < deadline:
            time.sleep(0.05)
        assert b.wire_drops == 4
        assert b.recv(0.3) is None  # none of them surfaced
        # and the transport still serves clean frames afterwards
        a = PeerTransport(0, addrs, policy=_policy())
        assert a.send(1, {"type": "ping"}) is True
        assert b.recv(2.0) is not None
    finally:
        b.close()


def test_sender_restart_epoch_resets_dedup_window():
    # a restarted peer reuses msg_id 0 under a NEWER incarnation epoch:
    # the window resets (crash/rejoin's first HELLO is not a "dup"), while
    # a dead incarnation's delayed frame (older epoch) is never handled
    ports = free_ports(2)
    addrs = [("127.0.0.1", p) for p in ports]
    b = PeerTransport(1, addrs, policy=_policy())
    b.start()
    try:
        def deliver(epoch, msg_id):
            s = socket.create_connection(("127.0.0.1", ports[1]),
                                         timeout=5.0)
            s.sendall(pack_frame({"type": "ping", "from": 0,
                                  "msg_id": msg_id, "msg_epoch": epoch},
                                 None))
            s.close()

        deliver(1000, 0)
        assert b.recv(2.0) is not None
        deliver(2000, 0)  # restarted sender, same id, newer epoch
        assert b.recv(2.0) is not None
        deliver(1000, 1)  # the dead incarnation's straggler
        assert b.recv(0.5) is None
        assert b.dups_dropped == 1
    finally:
        b.close()


def test_bounded_inbox_refuses_overflow_and_preserves_delivery():
    ports = free_ports(2)
    addrs = [("127.0.0.1", p) for p in ports]
    a = PeerTransport(0, addrs, policy=_policy())
    b = PeerTransport(1, addrs, policy=_policy(inbox_max=2))
    b.start()
    try:
        for i in range(2):
            assert a.send(1, {"n": i}) is True  # ack follows the enqueue
        assert b.inbox.qsize() == 2
        # inbox full: the frame is REFUSED (no ack — an acked-then-shed
        # frame would be unrecoverable), the send fails after its retries,
        # and the queue stays bounded
        assert a.send(1, {"n": 2}) is False
        assert b.inbox_overflow >= 1
        assert b.inbox.qsize() == 2
        # drain one slot: delivery to the same destination works again —
        # overflow shed nothing silently (at-least-once preserved)
        assert b.recv(1.0)[0]["n"] == 0
        assert a.send(1, {"n": 2}) is True
        assert b.recv(1.0)[0]["n"] == 1
        assert b.recv(1.0)[0]["n"] == 2
        assert b.dups_dropped == 0  # the refused frame was un-recorded
    finally:
        b.close()


# ------------------------------------------------------------- chaos lane


def test_wire_plan_validation():
    with pytest.raises(ValueError, match="wire_drop_prob"):
        FaultPlan(wire_drop_prob=1.5)
    with pytest.raises(ValueError, match="wire_delay_s"):
        FaultPlan(wire_delay_prob=0.5, wire_delay_s=-1.0)
    with pytest.raises(ValueError, match="silently never"):
        FaultPlan(wire_rounds=(0, 1))  # span with no armed probability
    with pytest.raises(ValueError, match="empty"):
        FaultPlan(wire_drop_prob=0.5, wire_rounds=())
    assert not FaultPlan().wire_enabled
    assert FaultPlan(wire_dup_prob=0.1).wire_enabled


def test_wire_actions_deterministic_and_round_scoped():
    plan = FaultPlan(seed=3, wire_drop_prob=0.5, wire_dup_prob=0.5,
                     wire_corrupt_prob=0.5, wire_rounds=(2, 3))
    assert plan.wire_actions(0, 0, 1, 0) is None  # outside the span
    a = plan.wire_actions(2, 0, 1, 7, attempt=0)
    assert a == plan.wire_actions(2, 0, 1, 7, attempt=0)  # replayable
    # a retry re-rolls its fate; distinct messages draw independently
    draws = {tuple(sorted(plan.wire_actions(2, 0, 1, m, attempt=k).items(),
                          key=str))
             for m in range(8) for k in range(2)}
    assert len(draws) > 1


def test_chaos_drop_exhausts_budget_and_dup_is_deduped():
    ports = free_ports(2)
    addrs = [("127.0.0.1", p) for p in ports]
    b = PeerTransport(1, addrs, policy=_policy())
    b.start()
    try:
        # drop=1.0: every attempt of every message vanishes
        a = PeerTransport(
            0, addrs, policy=_policy(),
            chaos=WireChaos(FaultPlan(wire_drop_prob=1.0), lambda: 0))
        assert a.send(1, {"type": "ping"}) is False
        assert a.chaos_injected["drop"] == 3  # initial + 2 retries
        assert b.recv(0.3) is None
        # dup=1.0: delivered once to the application, duplicate absorbed
        c = PeerTransport(
            2 % 2, addrs, policy=_policy(),
            chaos=WireChaos(FaultPlan(wire_dup_prob=1.0), lambda: 0))
        c._next_msg_id[1] = 100  # distinct id space from transport `a`
        assert c.send(1, {"type": "ping"}) is True
        assert b.recv(2.0) is not None
        assert b.recv(0.5) is None
        assert b.dups_dropped >= 1
    finally:
        b.close()


def test_chaos_corruption_is_caught_by_crc_and_healed_by_retry():
    ports = free_ports(2)
    addrs = [("127.0.0.1", p) for p in ports]
    b = PeerTransport(1, addrs, policy=_policy())
    b.start()
    try:
        # corrupt only attempt 0 of round 0 via the span: attempt draws
        # re-roll, so the retry goes through clean — self-healing in one
        # message's lifetime
        class OneShot:
            def __init__(self):
                self.plan = FaultPlan(wire_corrupt_prob=1.0)

            def actions(self, src, dst, msg_id, attempt, clock=None):
                if attempt > 0:
                    return None
                return self.plan.wire_actions(0, src, dst, msg_id, attempt)

        a = PeerTransport(0, addrs, policy=_policy(), chaos=OneShot())
        assert a.send(1, {"type": "ping"},
                      {"t": {"x": np.float32([1, 2, 3, 4])}}) is True
        assert a.retries == 1 and a.chaos_injected["corrupt"] == 1
        got = b.recv(3.0)
        assert got is not None
        np.testing.assert_array_equal(got[1]["t"]["x"], [1, 2, 3, 4])
        assert b.crc_drops == 1  # the corrupt copy died before parsing
    finally:
        b.close()


# ------------------------------------------------- pipelined sender (r11)


def test_send_async_per_destination_ordering_and_flush():
    """The pipelined seam's contract: msg_ids are allocated in enqueue
    order, the worker drains FIFO, so one destination's frames arrive in
    msg-id order; flush_sends blocks until the queue is drained AND the
    protocol completed for every frame."""
    ports = free_ports(2)
    addrs = [("127.0.0.1", p) for p in ports]
    a = PeerTransport(0, addrs, policy=_policy(pipeline_depth=2))
    b = PeerTransport(1, addrs, policy=_policy())
    b.start()
    try:
        for i in range(10):
            assert a.send_async(1, {"type": "ping", "n": i}) is True
        assert a.flush_sends(timeout_s=10.0) is True
        got = []
        msg = b.recv(2.0)
        while msg is not None:
            got.append((msg[0]["msg_id"], msg[0]["n"]))
            msg = b.recv(0.2)
        assert got == [(i, i) for i in range(10)]
        assert a.stats()["pipeline"]["async_enqueued"] == 10
        assert a.send_failures == 0
    finally:
        b.close()
        a.close()


def test_send_async_backpressure_blocks_on_full_queue():
    """Bounded handoff: with pipeline_depth=1 and an unreachable
    destination (every attempt burns the full retry schedule in the
    worker), the THIRD enqueue must BLOCK until the worker frees a slot —
    frames can never pile up beyond depth+1 per destination."""
    ports = free_ports(2)
    addrs = [("127.0.0.1", p) for p in ports]
    # nothing listens on peer 1: each logical send takes ~3 fast refused
    # connects + two ~10ms backoffs in the worker
    a = PeerTransport(0, addrs,
                      policy=_policy(pipeline_depth=1, retry_base_s=0.05,
                                     retry_max_s=0.1, down_after=100))
    try:
        t0 = time.time()
        assert a.send_async(1, {"type": "ping", "n": 0}) is True  # worker
        assert a.send_async(1, {"type": "ping", "n": 1}) is True  # queued
        fast = time.time() - t0
        assert fast < 0.5, "enqueue up to depth must not block"
        t0 = time.time()
        assert a.send_async(1, {"type": "ping", "n": 2}) is True
        blocked = time.time() - t0
        assert blocked > 0.02, ("third enqueue should have waited for the "
                                "worker to free a slot (back-pressure)")
        assert a.flush_sends(timeout_s=15.0) is True
        assert a.send_failures == 3  # all three exhausted their budgets
        assert a.stats()["pipeline"]["backpressure_blocks"] >= 1
    finally:
        a.close()


def test_send_async_under_wire_chaos_dedup_and_drop():
    """The pipeline composes with the wire chaos lane: dup=1.0 duplicates
    every delivery (the receiver's dedup window absorbs the copies — each
    logical send surfaces exactly once), and a drop=1.0 sender records its
    failures through the worker without ever blocking the enqueue path
    past the queue bound."""
    ports = free_ports(2)
    addrs = [("127.0.0.1", p) for p in ports]
    b = PeerTransport(1, addrs, policy=_policy())
    b.start()
    dup = PeerTransport(
        0, addrs, policy=_policy(),
        chaos=WireChaos(FaultPlan(wire_dup_prob=1.0), lambda: 0))
    try:
        for i in range(4):
            assert dup.send_async(1, {"type": "ping", "n": i}) is True
        assert dup.flush_sends(timeout_s=10.0) is True
        got = []
        msg = b.recv(2.0)
        while msg is not None:
            got.append(msg[0]["n"])
            msg = b.recv(0.3)
        assert got == [0, 1, 2, 3]  # once each, in order
        assert b.dups_dropped >= 4
        drop = PeerTransport(
            0, addrs, policy=_policy(),
            chaos=WireChaos(FaultPlan(wire_drop_prob=1.0), lambda: 0))
        drop._next_msg_id[1] = 100  # distinct id space from `dup`
        assert drop.send_async(1, {"type": "ping"}) is True
        assert drop.flush_sends(timeout_s=10.0) is True
        assert drop.send_failures == 1
        assert drop.chaos_injected["drop"] == 3  # initial + 2 retries
        assert b.recv(0.3) is None
    finally:
        b.close()
        dup.close()


# ----------------------------------------------------------- static guard


def test_every_dist_socket_op_has_a_deadline():
    """Static guard for the PR 7 invariant 'hard deadlines everywhere':
    every socket recv/recv_into/accept/connect call site under
    bcfl_tpu/dist must carry a visible deadline. A new call site without
    one fails HERE, not as a wedged peer in CI. Now a thin wrapper over
    the AST ``socket-deadline`` checker (bcfl_tpu.analysis, ANALYSIS.md),
    which resolves the actual call and its keyword args instead of the
    old ±3-line substring window — and covers ``recv_into``, which the
    substrings never matched; tests/test_analysis.py pins grep parity."""
    from bcfl_tpu.analysis import run_lint

    offenders = [
        f"{os.path.basename(f.file)}:{f.line}: {f.message}"
        for f in run_lint([os.path.join(REPO, "bcfl_tpu", "dist")],
                          checker_ids_filter=["socket-deadline"],
                          use_baseline=False)
        if f.failing]
    assert not offenders, (
        "socket call sites without a visible deadline "
        "(add a timeout or a '# deadline: ...' pointer):\n"
        + "\n".join(offenders))


def test_no_full_frame_payload_concat_outside_wire():
    """Static guard for the r11 zero-copy send path: no code outside
    ``wire.py`` may build a full frame payload as one ``bytes`` —
    ``pack_frame`` (the in-memory reference) must not be called from
    production code, and nothing under ``bcfl_tpu/dist`` may ``b"".join``
    a payload. A regression here silently doubles peak serialization
    memory per send (a model-sized copy), exactly what the streaming
    writer (``wire.write_frame``) exists to avoid. Now a thin wrapper
    over the AST ``no-frame-concat`` checker (bcfl_tpu.analysis,
    ANALYSIS.md), which flags real call sites instead of substrings."""
    from bcfl_tpu.analysis import run_lint

    pkg = os.path.join(REPO, "bcfl_tpu")
    offenders = [
        f"{os.path.relpath(f.file, pkg)}:{f.line}: {f.message}"
        for f in run_lint([pkg], checker_ids_filter=["no-frame-concat"],
                          use_baseline=False)
        if f.failing]
    assert not offenders, (
        "full-frame payload concatenation outside wire.py (stream via "
        "wire.write_frame instead):\n" + "\n".join(offenders))
